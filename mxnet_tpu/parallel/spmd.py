"""One mesh, one program: full SPMD parameter + activation sharding for
the fused train step and the serving bind (GSPMD, arXiv:2105.04663).

Everything the parallelism substrate shipped so far shards SOMETHING —
ZeRO-1 the optimizer update (`parallel/zero1.py`), the GPipe schedule the
compute-in-time dimension (`parallel/pipeline.py`), grad sync the wire
(`parallel/grad_sync.py`) — but WEIGHTS stayed fully replicated on every
device, so no model bigger than one replica's HBM was trainable or
servable. GSPMD says closing that is one refactor, not four: assign every
parameter a `PartitionSpec` over ONE mesh with named axes and let XLA's
SPMD partitioner propagate the layout through the already-jitted step.
This module is that planner plus the context the executor threads it
with:

* :func:`infer_param_sharding` — the partition planner. Matmul/conv
  weights alternate column-/row-parallel over ``tp`` along the graph's
  topo order (the Megatron pattern: activations stay sharded between a
  col→row pair, XLA inserts exactly one reduce per block instead of one
  per matmul); large parameters shard their biggest free dimension over
  ``fsdp`` (params all-gathered just-in-time inside the step, grads
  reduce-scattered back — composing with, not duplicating, the ZeRO-1
  update sharding); everything else replicates.
* :class:`SpmdContext` — owns the mesh (``MXNET_SPMD=tp=2,fsdp=2``
  style spec, axis order dp → pp → fsdp → tp so tp rides the shortest
  ICI hops), the per-parameter specs, batch sharding over ``dp``(+
  ``fsdp`` when divisible) INSIDE the fused program, placement of the
  bound buffers (`jax.device_put` once; steady state is a no-op), the
  in-trace constraints that keep gradients/updated weights/optimizer
  state at the planned layout (so donation aliases and state bytes
  follow the weight's 1/N), and the named ``CompileCache("spmd")`` every
  sharded step compiles under.

Composition:

* **ZeRO-1** — `Zero1Context.traced_update(unpack_shardings=...)`
  unpacks the updated flat buckets straight back to each parameter's
  planned sharding instead of replicating, so tp/fsdp weight sharding
  and dp update sharding live in the same program.
* **Pipeline** — inside the GPipe ``shard_map`` the mesh axes are
  manual, so GSPMD cannot propagate; placement there is residency-style:
  each placed parameter enters the schedule sharded (one mesh axis per
  dimension, ``pp`` first) and is all-gathered just-in-time at the top
  of the traced schedule (`lax.all_gather`; its transpose reduce-
  scatters the gradients back). Each device then HOLDS 1/S of the
  parameters between steps — the per-stage weight-placement memory
  claim — while the schedule's compute stays per-device.
* **Serving** — `place_params` is reused by `serving.Predictor` (bound
  weights sharded across the mesh, shared by every bucket executor) and
  `models.transformer` shards the generation KV slab's heads axis over
  ``tp`` (`model_mesh` makes `MXNET_SPMD` reach `TransformerLM`).

Gate: ``MXNET_SPMD`` (empty = off). Any plan or trace failure falls back
to the replicated fused step (`Module._spmd_failed`) — replicated
execution stays the correctness reference; sharded parity is ulp-level
(the PR 6 FMA-contraction precedent), rel <= 1e-5 over whole runs
(pinned by tests/python/unittest/test_spmd.py).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry
from ..base import getenv, register_env
from . import mesh as mesh_mod
from .collectives import sharding_constraint
from .partition import nbytes_on_device

__all__ = ["SpmdContext", "SpmdFallback", "spmd_enabled", "spmd_mesh",
           "model_mesh", "infer_param_sharding", "parse_spmd_spec"]

register_env("MXNET_SPMD", "",
             "SPMD parameter+activation sharding spec for the fused step "
             "and serving bind, as 'axis=size' pairs over dp/pp/fsdp/tp "
             "(e.g. 'tp=2,fsdp=2'; '-1' once absorbs the rest); empty = "
             "off (fully-replicated weights, the correctness reference). "
             "Plan/trace failures auto-fall back to the replicated step")
register_env("MXNET_SPMD_FSDP_MIN_SIZE", 65536,
             "smallest parameter (elements) the 'fsdp' axis shards; "
             "smaller ones replicate (gather overhead beats the bytes)")

_MATMUL_OPS = ("FullyConnected", "Convolution")


class SpmdFallback(Exception):
    """The spec/graph cannot run the sharded step; the caller should use
    the replicated fused step. Carries the reason — Module logs it once."""


def spmd_enabled():
    return bool(str(getenv("MXNET_SPMD") or "").strip())


def parse_spmd_spec(spec=None):
    """``MXNET_SPMD`` (or an explicit string) -> ordered {axis: size}.
    Axis order is forced to dp, pp, fsdp, tp (outermost -> innermost:
    jax.devices() enumeration is torus-contiguous on TPU, so the
    trailing axis gets the shortest ICI hops — tp innermost)."""
    spec = str(getenv("MXNET_SPMD") if spec is None else spec).strip()
    if not spec:
        return {}
    axes = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue  # tolerate trailing/doubled commas
        name, eq, size = part.partition("=")
        name = name.strip()
        try:
            if not eq or not name:
                raise ValueError
            axes[name] = int(size)
        except ValueError:
            raise SpmdFallback(
                "MXNET_SPMD: expected 'axis=size' pairs like 'tp=2,fsdp=2'"
                f", got {part!r} in {spec!r}") from None
    order = (mesh_mod.AXIS_DP, mesh_mod.AXIS_PP, mesh_mod.AXIS_FSDP,
             mesh_mod.AXIS_TP)
    unknown = [a for a in axes if a not in order]
    if unknown:
        raise SpmdFallback(
            f"MXNET_SPMD: unknown axes {unknown} (supported: {list(order)})")
    return {a: axes[a] for a in order if a in axes}


# (spec string, device ids) -> Mesh — matches() consults the mesh per
# step, and create_mesh is not free; keyed like mesh.default_mesh so a
# spec edit or device change invalidates instead of silently reusing
_mesh_memo = {}


def spmd_mesh(spec=None, devices=None):
    """The one mesh of the spec (a fully-fixed shape smaller than the
    device count takes the FIRST matching devices, like
    `mesh_from_env`). Raises :class:`SpmdFallback` on an unsatisfiable
    spec — the caller's cue to stay replicated."""
    if spec is None and devices is None:
        key = (str(getenv("MXNET_SPMD") or ""),
               tuple(d.id for d in jax.devices()))
        mesh = _mesh_memo.get(key)
        if mesh is None:
            mesh = _build_spmd_mesh(None, None)
            _mesh_memo.clear()  # one live entry: env edits invalidate
            _mesh_memo[key] = mesh
        return mesh
    return _build_spmd_mesh(spec, devices)


def _build_spmd_mesh(spec, devices):
    axes = parse_spmd_spec(spec)
    if not axes:
        raise SpmdFallback("MXNET_SPMD is empty")
    devices = list(devices if devices is not None else jax.devices())
    if -1 not in axes.values():
        total = int(np.prod(list(axes.values())))
        if total > len(devices):
            raise SpmdFallback(
                f"MXNET_SPMD={axes} needs {total} devices, "
                f"only {len(devices)} available")
        devices = devices[:total]
    try:
        return mesh_mod.create_mesh(devices=devices, **axes)
    except AssertionError as e:
        raise SpmdFallback(f"MXNET_SPMD mesh unsatisfiable: {e}") from e


def model_mesh():
    """The mesh functional models (`models.transformer.TransformerLM`)
    bind to by default: the `MXNET_SPMD` mesh when the gate is on (so
    serving/generation weights and the KV slab shard without plumbing),
    else the ambient/default mesh. Falls back to `default_mesh` when the
    spec is unsatisfiable — a model constructor must never die on a bad
    env var."""
    if spmd_enabled():
        try:
            return spmd_mesh()
        except SpmdFallback:
            pass
    return mesh_mod.default_mesh()


# ---------------------------------------------------------------------------
# The partition planner
# ---------------------------------------------------------------------------

def _axsz(mesh, ax):
    return mesh_mod.axis_size(mesh, ax)


def _matmul_params(symbol):
    """Walk the graph in topo order and yield (weight_name, bias_name)
    per matmul-like node (FullyConnected / Convolution) — the layer
    sequence the Megatron column/row alternation follows."""
    from ..symbol.symbol import _topo_order

    out = []
    for node in _topo_order([n for n, _ in symbol._outputs]):
        if node.is_variable or node.op not in _MATMUL_OPS:
            continue
        w = b = None
        for child, _oi in node.inputs:
            if not child.is_variable:
                continue
            if child.name.endswith("weight"):
                w = child.name
            elif child.name.endswith("bias"):
                b = child.name
        if w is not None:
            out.append((w, b))
    return out


def infer_param_sharding(mesh, symbol, param_shapes, fsdp_min_size=None,
                         residency_axes=None):
    """Partition specs for every parameter of ``symbol``:
    ``{name: PartitionSpec}`` over ``mesh``'s named axes.

    ``param_shapes``: {name: shape} of the bound parameters.

    Default (GSPMD) mode — tp column/row alternation along the topo
    order of matmul/conv nodes (col: weight dim 0 = the output features,
    and its bias, over 'tp'; row: weight dim 1 = the input features over
    'tp', bias replicated — activations stay tp-sharded between the pair
    and XLA inserts ONE reduce per block), then an fsdp pass sharding
    the largest still-free divisible dim of every parameter with >=
    ``fsdp_min_size`` elements (``MXNET_SPMD_FSDP_MIN_SIZE``). A layer
    whose weight doesn't divide by tp replicates and RESTARTS the
    alternation (the next matmul is column-parallel again).

    ``residency_axes`` (the pipeline-schedule mode): skip the Megatron
    alternation — inside the GPipe ``shard_map`` every axis is manual,
    so sharding is residency-only (params enter sharded, the traced
    schedule all-gathers them just-in-time). Shard each parameter's
    largest divisible dims over the given axes in order (one axis per
    dim, 'pp' first), same ``fsdp_min_size`` floor.
    """
    if fsdp_min_size is None:
        fsdp_min_size = int(getenv("MXNET_SPMD_FSDP_MIN_SIZE"))
    specs = {name: [None] * len(shape)
             for name, shape in param_shapes.items()}

    if residency_axes is not None:
        axes = [a for a in residency_axes if _axsz(mesh, a) > 1]
        for name, shape in param_shapes.items():
            if int(np.prod(shape) if shape else 1) < fsdp_min_size:
                continue
            parts = specs[name]
            for ax in axes:
                n = _axsz(mesh, ax)
                # largest still-free dim divisible by this axis
                cand = [d for d in range(len(shape))
                        if parts[d] is None and shape[d] % n == 0
                        and shape[d] >= n]
                if not cand:
                    continue
                parts[max(cand, key=lambda d: shape[d])] = ax
        return {n: P(*p) for n, p in specs.items()}

    tp = _axsz(mesh, mesh_mod.AXIS_TP)
    if tp > 1:
        col = True  # alternation state: column-parallel first
        for w, b in _matmul_params(symbol):
            shape = param_shapes.get(w)
            if shape is None or len(shape) < 2:
                continue
            dim = 0 if col else 1
            if shape[dim] % tp != 0:
                col = True  # broken chain: restart the alternation
                continue
            specs[w][dim] = mesh_mod.AXIS_TP
            if col and b is not None and b in param_shapes and \
                    param_shapes[b] and param_shapes[b][0] % tp == 0:
                # column-parallel bias lives on the sharded output dim
                specs[b][0] = mesh_mod.AXIS_TP
            col = not col

    fsdp = _axsz(mesh, mesh_mod.AXIS_FSDP)
    if fsdp > 1:
        for name, shape in param_shapes.items():
            if int(np.prod(shape) if shape else 1) < fsdp_min_size:
                continue
            parts = specs[name]
            cand = [d for d in range(len(shape))
                    if parts[d] is None and shape[d] % fsdp == 0
                    and shape[d] >= fsdp]
            if cand:
                parts[max(cand, key=lambda d: shape[d])] = \
                    mesh_mod.AXIS_FSDP
    return {n: P(*p) for n, p in specs.items()}


# ---------------------------------------------------------------------------
# The context the fused step threads
# ---------------------------------------------------------------------------

class SpmdContext:
    """One module's sharding plan: the mesh, per-parameter specs, batch
    sharding, buffer placement and the in-trace constraints. Owned by
    `Module` (the `Zero1Context`/`PipelineContext` lifecycle: built
    lazily at the first fused step, `matches()` re-validated per step,
    any failure falls back to the replicated fused step)."""

    def __init__(self, mesh, specs, batch_dims, arg_names,
                 pipeline_mode=False):
        self.mesh = mesh
        self.specs = dict(specs)               # param name -> PartitionSpec
        self.batch_dims = dict(batch_dims)     # batch input name -> spec
        self.pipeline_mode = bool(pipeline_mode)
        self._arg_names = tuple(arg_names)
        self.repl = NamedSharding(mesh, P())
        self._shardings = {}                   # name -> NamedSharding memo
        # the named cache every sharded-step executable compiles under —
        # PER CONTEXT, not process-global (the PipelineContext precedent:
        # the jitted step closes over the executor, and a global cache
        # would pin every module it served alive); the monotonic
        # named_stats("spmd") totals still aggregate across contexts
        from ..compile_cache import CompileCache

        self.cache = CompileCache("spmd", maxsize=8)
        # measured (per_device, total) param bytes — the layouts are
        # invariant per plan, so the addressable_shards walk happens once
        # (lazily, after the first placed step), not per record_step
        self._param_bytes = None

    # -- construction --------------------------------------------------------

    @staticmethod
    def build(symbol, executor, data_names, label_names, pipeline=False):
        """Plan the sharding for a bound executor, or raise
        :class:`SpmdFallback` with the reason."""
        mesh = spmd_mesh()
        if all(s <= 1 for s in mesh.shape.values()):
            raise SpmdFallback("MXNET_SPMD resolves to a 1-device mesh")
        arg_names = executor._arg_names
        batch_names = [n for n in list(data_names) + list(label_names)
                       if n in executor.arg_dict]
        param_shapes = {n: tuple(executor.arg_dict[n].shape)
                        for n in arg_names if n not in batch_names}
        if pipeline:
            specs = infer_param_sharding(
                mesh, symbol, param_shapes,
                residency_axes=(mesh_mod.AXIS_PP, mesh_mod.AXIS_FSDP,
                                mesh_mod.AXIS_TP))
        else:
            specs = infer_param_sharding(mesh, symbol, param_shapes)
        # batch sharding over dp (+fsdp when divisible) INSIDE the fused
        # program — the in-program data parallelism that used to exist
        # only as cross-process grad sync. Pipeline mode keeps the batch
        # replicated: the schedule's micro-batch split owns that dim.
        batch_dims = {}
        if not pipeline:
            for n in batch_names:
                shape = tuple(executor.arg_dict[n].shape)
                axes = []
                div = 1
                for ax in (mesh_mod.AXIS_DP, mesh_mod.AXIS_FSDP):
                    sz = _axsz(mesh, ax)
                    if sz > 1 and shape and \
                            shape[0] % (div * sz) == 0:
                        axes.append(ax)
                        div *= sz
                if axes:
                    parts = [tuple(axes) if len(axes) > 1 else axes[0]]
                    parts += [None] * (len(shape) - 1)
                    batch_dims[n] = P(*parts)
        sharded_any = any(a is not None
                          for s in specs.values() for a in tuple(s))
        if not sharded_any and not batch_dims:
            raise SpmdFallback(
                "no parameter or batch dimension divides the "
                f"MXNET_SPMD mesh {dict(mesh.shape)}")
        ctx = SpmdContext(mesh, specs, batch_dims, arg_names,
                          pipeline_mode=pipeline)
        ctx._bound_sig = SpmdContext._exec_sig(executor)
        return ctx

    @staticmethod
    def _exec_sig(executor):
        return tuple((n, tuple(executor.arg_dict[n].shape),
                      str(executor.arg_dict[n].dtype))
                     for n in executor._arg_names)

    def matches(self, executor, pipeline_active=False):
        """Whether this plan still fits the executor's bound layout, the
        env spec, and the pipeline gate (a pipeline appearing or
        disappearing flips the planner mode, so the plan rebuilds)."""
        if bool(pipeline_active) != self.pipeline_mode:
            return False
        try:
            if spmd_mesh() is not self.mesh and \
                    mesh_mod.devices_key(spmd_mesh()) != \
                    mesh_mod.devices_key(self.mesh):
                return False
        except SpmdFallback:
            return False
        try:
            return SpmdContext._exec_sig(executor) == self._bound_sig
        except KeyError:
            return False

    def key(self):
        """Compile-cache key component: everything that changes the
        sharded step's layout."""
        return ("spmd", mesh_mod.devices_key(self.mesh),
                tuple(sorted((n, tuple(s)) for n, s in self.specs.items())),
                tuple(sorted((n, tuple(s))
                             for n, s in self.batch_dims.items())),
                self.pipeline_mode)

    # -- shardings -----------------------------------------------------------

    def sharding(self, name, shape=None):
        """The planned NamedSharding of one bound argument (params by
        spec, batch inputs by batch spec, everything else replicated)."""
        s = self._shardings.get(name)
        if s is None:
            if name in self.specs:
                spec = self.specs[name]
            elif name in self.batch_dims:
                spec = self.batch_dims[name]
            else:
                spec = P()
            s = NamedSharding(self.mesh, spec)
            self._shardings[name] = s
        return s

    def pp_spec(self, name):
        """The residency spec the pipeline schedule gathers from (None
        for replicated params — they enter the shard_map with P())."""
        spec = self.specs.get(name)
        if spec is None or not any(a is not None for a in tuple(spec)):
            return None
        return spec

    def put(self, name, x):
        """Commit one bound argument onto the mesh at its planned
        sharding. Steady state is a no-op (weights/state come back from
        the previous step already placed); per-step feeds transfer once
        here."""
        arr = x if isinstance(x, jax.Array) or not hasattr(x, "_data") \
            else x._data
        tgt = self.sharding(name)
        try:
            if getattr(arr, "sharding", None) == tgt:
                return arr
        except Exception:  # noqa: BLE001 — fall through to device_put
            pass
        return jax.device_put(arr, tgt)

    def put_replicated(self, x):
        arr = x if isinstance(x, jax.Array) or not hasattr(x, "_data") \
            else x._data
        try:
            if getattr(arr, "sharding", None) == self.repl:
                return arr
        except Exception:  # noqa: BLE001
            pass
        return jax.device_put(arr, self.repl)

    def place_params(self, names, weights):
        """One-time physical placement of bound parameter NDArrays (the
        per-device residency drop to ~1/N happens HERE, before the first
        sharded step, so donation aliases from step one)."""
        for n, w in zip(names, weights):
            w._data = self.put(n, w._data)

    def place_state_trees(self, names, state_trees):
        """Place per-parameter optimizer-state NDArray leaves at the
        owning parameter's sharding (a state leaf shaped like the weight
        shards with it — Adam moments, momentum, fp32 master weights;
        anything else replicates). Optimizer-state bytes then follow the
        parameter's 1/N."""
        for n, st in zip(names, state_trees):
            if st is None:
                continue
            for leaf in _state_nd_leaves(st):
                tgt = self.sharding(n) \
                    if tuple(leaf.shape) == self._param_shape(n) \
                    else self.repl
                try:
                    if getattr(leaf._data, "sharding", None) == tgt:
                        continue
                except Exception:  # noqa: BLE001
                    pass
                leaf._data = jax.device_put(leaf._data, tgt)

    def _param_shape(self, name):
        sig = getattr(self, "_bound_sig", ())
        for n, shape, _dt in sig:
            if n == name:
                return shape
        return None

    # -- in-trace constraints ------------------------------------------------

    def constrain(self, name, x):
        return sharding_constraint(x, self.sharding(name))

    def constrain_grads(self, names, grads):
        """Pin each gradient to its parameter's layout (with the
        upstream batch-sharded sum this lowers to the fsdp
        reduce-scatter; tp grads stay tp-local)."""
        return tuple(self.constrain(n, g) for n, g in zip(names, grads))

    def constrain_params(self, names, ws):
        return tuple(self.constrain(n, w) for n, w in zip(names, ws))

    def constrain_state_trees(self, names, state_trees):
        """Pin updated state leaves to the owning parameter's layout
        (leaves shaped like the weight; others replicated)."""
        from jax import tree_util as jtu

        out = []
        for n, st in zip(names, state_trees):
            shape = self._param_shape(n)

            def pin(leaf, n=n, shape=shape):
                if hasattr(leaf, "shape") and tuple(leaf.shape) == shape:
                    return sharding_constraint(leaf, self.sharding(n))
                return leaf

            out.append(jtu.tree_map(pin, st))
        return out

    def param_shardings(self, names):
        return [self.sharding(n) for n in names]

    def unplace(self, executor, updater=None):
        """Re-replicate every buffer `place_params`/`place_state_trees`
        sharded (called on the fallback path: the replicated fused step
        must see the same layouts it would without the gate — a failed
        sharded attempt must not leave 1/N buffers behind)."""
        for nd_ in list(executor.arg_dict.values()) + \
                list(executor.aux_dict.values()):
            try:
                if getattr(nd_._data, "sharding", None) != self.repl:
                    nd_._data = jax.device_put(nd_._data, self.repl)
            except Exception:  # noqa: BLE001 — best effort, never fatal
                pass
        if updater is not None:
            for st in updater.states.values():
                for leaf in _state_nd_leaves(st):
                    try:
                        if getattr(leaf._data, "sharding", None) != \
                                self.repl:
                            leaf._data = jax.device_put(leaf._data,
                                                        self.repl)
                    except Exception:  # noqa: BLE001
                        pass

    # -- accounting ----------------------------------------------------------

    def param_bytes_per_device(self, names, weights):
        """Measured parameter bytes resident on ONE device (physical
        shard residency, not the annotation) vs the replicated total."""
        per_dev = 0
        total = 0
        for n, w in zip(names, weights):
            arr = w._data if hasattr(w, "_data") else w
            per_dev += nbytes_on_device(arr)
            total += int(arr.size) * arr.dtype.itemsize
        return per_dev, total

    def record_step(self, names=None, weights=None):
        """Per-step telemetry (called by `Executor.fused_step` after a
        successful sharded dispatch — the gauges re-set here so
        telemetry enabled mid-run still reports the mesh next to the
        counters)."""
        if not telemetry._enabled:
            return
        telemetry.counter("spmd.steps").inc()
        for ax in (mesh_mod.AXIS_DP, mesh_mod.AXIS_TP, mesh_mod.AXIS_FSDP,
                   mesh_mod.AXIS_PP):
            telemetry.gauge(f"spmd.{ax}").set(_axsz(self.mesh, ax))
        if names is not None and weights is not None:
            if self._param_bytes is None:
                self._param_bytes = \
                    self.param_bytes_per_device(names, weights)
            per_dev, total = self._param_bytes
            telemetry.gauge("spmd.param_bytes_per_device").set(per_dev)
            telemetry.gauge("spmd.param_bytes_total").set(total)


def place_serving_params(symbol, arg_params, aux_params=None):
    """Shard a serving checkpoint's bound weights over the `MXNET_SPMD`
    mesh (the Predictor bind path): plan specs with
    :func:`infer_param_sharding` and `jax.device_put` each parameter
    NDArray in place — every bucket executor then binds the SAME sharded
    buffers, so serving weights stop being replicated (per-device
    residency ~1/N, measured by the census). Aux states replicate on the
    mesh. Inference jits pick the layout up from the committed inputs
    and GSPMD propagates — no executor change needed. Returns
    ``(mesh, specs)``; raises :class:`SpmdFallback` when the spec is
    unsatisfiable (caller stays replicated)."""
    mesh = spmd_mesh()
    if all(s <= 1 for s in mesh.shape.values()):
        raise SpmdFallback("MXNET_SPMD resolves to a 1-device mesh")
    shapes = {n: tuple(a.shape) for n, a in arg_params.items()}
    specs = infer_param_sharding(mesh, symbol, shapes)
    repl = NamedSharding(mesh, P())
    for n, a in arg_params.items():
        a._data = jax.device_put(a._data, NamedSharding(mesh, specs[n]))
    for a in (aux_params or {}).values():
        a._data = jax.device_put(a._data, repl)
    if telemetry._enabled:
        per_dev = sum(nbytes_on_device(a._data)
                      for a in arg_params.values())
        telemetry.gauge("spmd.serving_param_bytes_per_device").set(per_dev)
    return mesh, specs


def _state_nd_leaves(st):
    """NDArray leaves of one optimizer-state tree (the
    `_state_to_jax` structure walk, yielding the mutable wrappers)."""
    if st is None:
        return
    if isinstance(st, (tuple, list)):
        for x in st:
            yield from _state_nd_leaves(x)
    elif hasattr(st, "_data"):
        yield st
