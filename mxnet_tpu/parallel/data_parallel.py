"""Sharded SPMD training: the TPU-native `trainer.step`.

The reference's data-parallel step is push/pull per parameter through
KVStore (`gluon/trainer.py:298,327` → `kvstore_local.h`/`kvstore_dist.h`):
reduce grads across devices, run the optimizer, broadcast weights. Here the
WHOLE step — forward, backward, gradient AllReduce, optimizer — is ONE
jitted SPMD program over the mesh: batch sharded on dp×sp, parameters
replicated (or sharded by fsdp/tp rules), XLA inserting the collectives.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import default_mesh
from .partition import infer_param_sharding


def replicate(tree, mesh=None):
    mesh = mesh or default_mesh()
    sh = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def shard_batch(tree, mesh=None, axes=("dp", "fsdp")):
    """Place a host batch onto the mesh, sharded on its leading dim over
    every present data axis (`executor_group.py:65` _split_input_slice)."""
    mesh = mesh or default_mesh()
    data_axes = tuple(a for a in axes if a in mesh.shape and mesh.shape[a] > 1)
    spec = P(data_axes if data_axes else None)
    sh = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


class ShardedTrainer:
    """Compile a gluon net + loss + optimizer into one sharded train step.

    Usage::

        trainer = ShardedTrainer(net, loss_fn, optimizer, mesh)
        for x, y in batches:
            loss = trainer.step(x, y)       # host numpy in, loss out

    `net` must be a HybridBlock whose forward was traced once (the trainer
    does this). Parameters/optimizer state live as sharded jax arrays inside
    the trainer (functional style); `sync_to_net()` writes them back into
    the gluon Parameters for save_parameters/export.
    """

    def __init__(self, net, loss_fn, optimizer, mesh=None, sample_input=None,
                 param_sharding=None, dtype=None):
        from .. import autograd  # noqa: F401 (net tracing path)
        from ..ndarray import NDArray

        self.net = net
        self.mesh = mesh or default_mesh()
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._step_fn = None
        self._dtype = dtype

        if sample_input is not None:
            self._build(sample_input, param_sharding)

    # -- build --------------------------------------------------------------

    def _build(self, sample_input, param_sharding=None):
        from ..ndarray import NDArray

        net = self.net
        x_nd = sample_input if isinstance(sample_input, NDArray) else NDArray(jnp.asarray(sample_input))
        _ = net(x_nd)  # builds cached op & binds params
        cop = net._cached_op
        assert cop is not None, "net must be hybridized (net.hybridize())"
        self._fwd = cop._traced(True)
        self._params_meta = net._cached_graph_params
        params = [p.data()._data for p in self._params_meta]

        mesh = self.mesh
        if param_sharding is None:
            shardings = [infer_param_sharding(mesh, p.name, arr.shape)
                         for p, arr in zip(self._params_meta, params)]
        else:
            shardings = [param_sharding.sharding_for(mesh, p.name, arr.shape)
                         for p, arr in zip(self._params_meta, params)]
        self._param_shardings = shardings
        self.params = [jax.device_put(a, s) for a, s in zip(params, shardings)]

        opt = self.optimizer
        self.opt_state = opt.init_flat(self.params) if hasattr(opt, "init_flat") else \
            [tuple(jnp.zeros_like(p) for _ in range(_n_slots(opt))) for p in self.params]

        fwd = self._fwd
        loss_fn = self.loss_fn

        def compute_loss(params, key, x, y):
            out = fwd(key, *params, x)
            out = out[0] if isinstance(out, tuple) else out
            return loss_fn(out, y)

        def step(params, opt_state, key, x, y, lr, t):
            loss, grads = jax.value_and_grad(compute_loss)(params, key, x, y)
            new_params, new_state = [], []
            for p, g, s in zip(params, grads, opt_state):
                np_, ns = _apply_opt(opt, p, g, s, lr, t)
                new_params.append(np_)
                new_state.append(ns)
            return new_params, new_state, loss

        repl = NamedSharding(mesh, P())
        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.shape and mesh.shape[a] > 1)
        data_sh = NamedSharding(mesh, P(data_axes if data_axes else None))
        self._data_sharding = data_sh

        state_shardings = [tuple(s for _ in st) if isinstance(st, tuple) else s
                           for st, s in zip(self.opt_state, shardings)]
        self._step_fn = jax.jit(
            step,
            in_shardings=(shardings, state_shardings, repl, data_sh, data_sh, repl, repl),
            out_shardings=(shardings, state_shardings, repl),
        )

    # -- step ---------------------------------------------------------------

    def step(self, x, y):
        from .. import random as _random
        from ..ndarray import NDArray

        if isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        x = jax.device_put(jnp.asarray(x), self._data_sharding)
        y = jax.device_put(jnp.asarray(y), self._data_sharding)
        key = _random.next_key()
        opt = self.optimizer
        opt.num_update += 1
        lr_val = opt.lr_scheduler(opt.num_update) if opt.lr_scheduler is not None else opt.lr
        lr = jnp.asarray(lr_val, jnp.float32)
        t = jnp.asarray(opt.num_update, jnp.int32)
        with self.mesh:
            self.params, self.opt_state, loss = self._step_fn(
                self.params, self.opt_state, key, x, y, lr, t)
        return loss

    def sync_to_net(self):
        """Write trained values back into the gluon Parameters."""
        from ..ndarray import NDArray

        for p, arr in zip(self._params_meta, self.params):
            p.set_data(NDArray(jax.device_get(arr)))


def _n_slots(opt):
    name = type(opt).__name__.lower()
    if "sgd" in name and getattr(opt, "momentum", 0):
        return 1
    if "adam" in name or "ftml" in name or "nadam" in name:
        return 2
    if "rmsprop" in name:
        return 2 if getattr(opt, "centered", False) else 1
    return 1 if name not in ("sgd",) else 0


def _apply_opt(opt, p, g, state, lr, t=None):
    """Functional optimizer update on raw jax arrays.

    Mirrors the fused update ops of `src/operator/optimizer_op.cc` for the
    common cases; other optimizers fall back to SGD semantics + their
    stateless pieces. wd comes from the optimizer object.
    """
    wd = jnp.asarray(getattr(opt, "wd", 0.0), p.dtype)
    name = type(opt).__name__.lower()
    rescale = jnp.asarray(getattr(opt, "rescale_grad", 1.0), p.dtype)
    g = g * rescale
    clip = getattr(opt, "clip_gradient", None)
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    g = g + wd * p

    if name == "sgd" and not getattr(opt, "momentum", 0):
        return p - lr.astype(p.dtype) * g, state
    if "sgd" in name or name == "nag":
        (m,) = state if isinstance(state, tuple) else (state,)
        mom = jnp.asarray(getattr(opt, "momentum", 0.9), p.dtype)
        m = mom * m + g
        if name == "nag":
            upd = g + mom * m
        else:
            upd = m
        return p - lr.astype(p.dtype) * upd, (m,)
    if "adam" in name:
        m, v = state
        # bias correction in float32 from the raw Python floats — routing the
        # betas through p.dtype first would round 0.999 to 1.0 in bfloat16
        # and freeze the update entirely
        b1f = jnp.asarray(getattr(opt, "beta1", 0.9), jnp.float32)
        b2f = jnp.asarray(getattr(opt, "beta2", 0.999), jnp.float32)
        b1 = b1f.astype(p.dtype)
        b2 = b2f.astype(p.dtype)
        eps = jnp.asarray(getattr(opt, "epsilon", 1e-8), p.dtype)
        tt = jnp.asarray(1 if t is None else t, jnp.float32)
        lr_t = lr * jnp.sqrt(1.0 - jnp.power(b2f, tt)) / (1.0 - jnp.power(b1f, tt))
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        return p - lr_t.astype(p.dtype) * m / (jnp.sqrt(v) + eps), (m, v)
    # generic fallback: plain SGD on the rescaled grad
    return p - lr.astype(p.dtype) * g, state
