"""Collective communication primitives.

The reference's collectives are NCCL calls (`kvstore_nccl.h`), hand-built
reduce trees (`comm.h:451`, `comm_tree.h:50`), and ps-lite RPC
(`kvstore_dist.h`). Here each primitive has two faces:

* **in-program** (inside `shard_map`/`jit`): thin wrappers over
  `jax.lax` collectives — XLA schedules them onto ICI.
* **eager** (NDArray level, outside jit): a tiny jitted program built on
  demand — the analogue of the reference pushing a reduction lambda onto
  the engine (`comm.h Reduce`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # newer jax exports it top-level
    from jax import shard_map as _jax_shard_map
except ImportError:  # older jax: experimental namespace only
    from jax.experimental.shard_map import shard_map as _jax_shard_map


def shard_map(f, **kwargs):
    """Version-stable `shard_map`: jax renamed the replication-check kwarg
    (`check_rep` -> `check_vma`) and moved the function out of
    `jax.experimental`; route every in-repo use through this shim."""
    import inspect

    try:
        params = inspect.signature(_jax_shard_map).parameters
    except (TypeError, ValueError):
        params = {}
    if "check_vma" in kwargs and "check_vma" not in params:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in params:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _jax_shard_map(f, **kwargs)


from ..compile_cache import CompileCache
from .mesh import default_mesh


# -- in-program (use inside shard_map) --------------------------------------

def all_reduce(x, axis_name, op="sum"):
    """AllReduce along a mesh axis (NCCL allreduce / `comm.h` Reduce+Bcast)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


psum_scatter = reduce_scatter


def sharding_constraint(x, sharding):
    """Version-stable `with_sharding_constraint` — the GSPMD annotation the
    sharded-weight-update paper (arXiv:2004.13336) is built on: a psum
    followed by a constraint to a sharded layout lowers to ReduceScatter,
    a constraint from sharded back to replicated lowers to AllGather."""
    from jax import lax as _lax

    return _lax.with_sharding_constraint(x, sharding)


def ppermute(x, axis_name, perm):
    """Point-to-point ring shift; the building block of ring attention."""
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name, axis_size, shift=1):
    """Send this shard to rank+shift (mod n) — one ICI hop on a torus."""
    perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis_name, perm)


# -- eager (NDArray / host level) -------------------------------------------

# the eager-collective programs, named so `named_stats("collectives")`
# attributes wire recompiles (was an anonymous lru_cache — the class
# tpulint's executable-cache rule now flags); track_memory=False — tiny
# one-op reduce programs, no /memory insight worth an AOT recompile
_eager_cache = CompileCache("collectives", track_memory=False)


def _eager_allreduce_fn(mesh, axis, op):
    def build():
        spec = P(axis)

        def body(x):
            return all_reduce(x, axis, op)

        return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec))

    return _eager_cache.get_or_build((mesh, axis, op), build)


def _flat_collective_mesh(mesh):
    """1-D view of `mesh` for eager collectives (a multi-axis mesh would
    otherwise mis-shape the stacked leading dim)."""
    import numpy as _np
    from jax.sharding import Mesh

    if len(mesh.axis_names) == 1:
        return mesh, mesh.axis_names[0]
    flat = Mesh(_np.asarray(mesh.devices).reshape(-1), ("_all",))
    return flat, "_all"


def eager_all_reduce(value, axis=None, op="sum", mesh=None):
    """AllReduce a replicated-per-device stacked value eagerly.

    ``value``: array whose leading dim is the mesh-axis size (one slice per
    device) — HOST-LOCAL slices in a multi-process job. Returns the same
    (global) shape with every slice = the reduction.
    """
    mesh = mesh or default_mesh()
    if axis is None or axis not in mesh.axis_names:
        mesh, axis = _flat_collective_mesh(mesh)
    if jax.process_count() > 1 and not isinstance(value, jax.Array):
        # host-local stacked slices → global array (non-addressable shards
        # can't be fed from a host-local jnp array)
        from jax.experimental import multihost_utils

        value = multihost_utils.host_local_array_to_global_array(
            value, mesh, P(axis))
    return _eager_allreduce_fn(mesh, axis, op)(value)


def barrier(mesh=None):
    """Block until all devices reach this point (reference
    `KVStore::Barrier`, `kvstore_dist.h:105`): a tiny psum over the mesh."""
    import numpy as _np

    from .. import analysis

    if analysis._enabled:
        # a barrier parks this thread until every peer arrives: any
        # tracked lock held here can deadlock the whole fleet (the
        # assist-vs-worker class from PR 12)
        analysis.check_blocking("collective.barrier")

    mesh = mesh or default_mesh()
    mesh, axis = _flat_collective_mesh(mesh)
    local = _np.ones((jax.local_device_count() if jax.process_count() > 1
                      else mesh.shape[axis],), _np.int32)
    out = eager_all_reduce(local, axis=axis, mesh=mesh)
    jax.block_until_ready(out)
    return int(out.addressable_shards[0].data[0]) if jax.process_count() > 1 else int(out[0])
