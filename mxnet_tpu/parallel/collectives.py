"""Collective communication primitives.

The reference's collectives are NCCL calls (`kvstore_nccl.h`), hand-built
reduce trees (`comm.h:451`, `comm_tree.h:50`), and ps-lite RPC
(`kvstore_dist.h`). Here each primitive has two faces:

* **in-program** (inside `shard_map`/`jit`): thin wrappers over
  `jax.lax` collectives — XLA schedules them onto ICI.
* **eager** (NDArray level, outside jit): a tiny jitted program built on
  demand — the analogue of the reference pushing a reduction lambda onto
  the engine (`comm.h Reduce`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import default_mesh


# -- in-program (use inside shard_map) --------------------------------------

def all_reduce(x, axis_name, op="sum"):
    """AllReduce along a mesh axis (NCCL allreduce / `comm.h` Reduce+Bcast)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


psum_scatter = reduce_scatter


def ppermute(x, axis_name, perm):
    """Point-to-point ring shift; the building block of ring attention."""
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name, axis_size, shift=1):
    """Send this shard to rank+shift (mod n) — one ICI hop on a torus."""
    perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis_name, perm)


# -- eager (NDArray / host level) -------------------------------------------

@functools.lru_cache(maxsize=None)
def _eager_allreduce_fn(mesh, axis, op):
    spec = P(axis)

    def body(x):
        return all_reduce(x, axis, op)

    from jax import shard_map
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec))


def eager_all_reduce(value, axis=None, op="sum", mesh=None):
    """AllReduce a replicated-per-device stacked value eagerly.

    ``value``: array whose leading dim is the mesh-axis size (one slice per
    device). Returns the same shape with every slice = the reduction.
    """
    mesh = mesh or default_mesh()
    axis = axis or mesh.axis_names[0]
    return _eager_allreduce_fn(mesh, axis, op)(value)


def barrier(mesh=None):
    """Block until all devices reach this point (reference
    `KVStore::Barrier`, `kvstore_dist.h:105`): a tiny psum over the mesh."""
    mesh = mesh or default_mesh()
    axis = mesh.axis_names[0]
    n = mesh.shape[axis]
    out = eager_all_reduce(jnp.ones((n,), jnp.int32), axis=axis, mesh=mesh)
    jax.block_until_ready(out)
    return int(out[0])
