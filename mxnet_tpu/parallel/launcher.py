"""Multi-host bootstrap — the `tools/launch.py` / dmlc_tracker replacement.

The reference launches a scheduler + servers + workers over ssh/mpi/yarn
(`tools/launch.py:71-73`). TPU pods need none of that: every host runs the
same SPMD program and rendezvous goes through the TPU runtime (or an
explicit coordinator for CPU/multi-process testing). This module reads the
environment and initialises the process group once.
"""
from __future__ import annotations

import os

import jax


def initialize_from_env():
    """Initialise jax.distributed if env describes a multi-process job.

    Recognised (first match wins):
      * TPU pod runtime env (JAX auto-detects) — nothing to do.
      * MXNET_COORDINATOR / MXNET_NUM_PROCESSES / MXNET_PROCESS_ID
      * DMLC_PS_ROOT_URI / DMLC_NUM_WORKER / DMLC_WORKER_ID (reference
        ps-lite names, minus servers+scheduler)
      * OMPI_COMM_WORLD_* (mpirun)
    """
    from . import elastic
    from .dist import init_process_group

    try:
        if os.environ.get("MXNET_COORDINATOR"):
            init_process_group(
                coordinator=os.environ["MXNET_COORDINATOR"],
                num_processes=int(os.environ.get("MXNET_NUM_PROCESSES", "1")),
                process_id=int(os.environ.get("MXNET_PROCESS_ID", "0")),
            )
            return True
        if os.environ.get("DMLC_PS_ROOT_URI"):
            init_process_group()
            return True
        if os.environ.get("OMPI_COMM_WORLD_SIZE"):
            init_process_group(
                coordinator=os.environ.get("MXNET_COORDINATOR", "127.0.0.1:9091"),
                num_processes=int(os.environ["OMPI_COMM_WORLD_SIZE"]),
                process_id=int(os.environ["OMPI_COMM_WORLD_RANK"]),
            )
            return True
        return False
    finally:
        # arm the elastic heartbeat lease on EVERY outcome (no-op unless
        # MXNET_ELASTIC=1 with a shared dir and peers): a shrunk-to-one
        # resumed worker takes the `return False` path above but must
        # still be a clean no-op here, and scripts that call this without
        # a coordinator still get the detector when the launcher armed it
        elastic.ensure_started()
