"""Elastic multi-worker runtime: heartbeat leases, worker-death detection,
and shrink-rendezvous resume.

PR 1's resilience layer detects stragglers and corrupt epochs but never
closes the loop: a dead worker today is everyone else parked forever in a
collective (the `dist.barrier` straggler warning logs and keeps waiting).
This module closes it:

* **Heartbeat leases** — every rank's :class:`Heartbeater` thread renews
  a per-rank lease file under ``MXNET_ELASTIC_DIR`` every
  ``MXNET_ELASTIC_HEARTBEAT_S``; a peer whose lease is older than
  ``MXNET_ELASTIC_GRACE_S`` is declared lost.
* **Guarded collectives** — `dist._allreduce_sum` / `_allgather` /
  `barrier` route through :meth:`ElasticRuntime.guard`: the collective
  runs on a worker thread while the caller polls the leases, so a worker
  death (or wedge) raises :class:`resilience.WorkerLostError` inside the
  training loop instead of blocking forever. A collective that merely
  runs slow with every lease fresh is never interrupted — the grace
  window bounds *stall with a dead peer*, not honest slowness.
* **Shrink rendezvous** — survivors agree on the new membership through
  generation-scoped join files (:meth:`ElasticRuntime.shrink`): new
  contiguous ranks, new world size, and a fresh coordinator chosen by the
  new rank 0. :meth:`ElasticRuntime.exec_resume` then re-execs the
  process image into the new process group (the torchelastic restart
  trampoline, minus the extra agent process) — the mesh, the grad-sync
  bucket plan, and the ZeRO-1 shard group all re-derive from the new
  world size on the way back up, and the training script resumes from
  the latest good checkpoint via `model.load_checkpoint`'s corrupt-epoch
  fallback (``begin_epoch = loaded + 1``). In-process jax re-init after
  losing a peer is NOT attempted: the runtime's device topology is baked
  at backend init, and a half-dead process group is unrecoverable state
  — re-exec is the honest, testable path (tests/dist/elastic_smoke.py).

Telemetry: ``elastic.generation`` / ``elastic.world_size`` gauges,
``elastic.lost_workers`` / ``elastic.shrinks`` counters,
``elastic.shrink_us`` latency histogram, plus an ``elastic.shrink``
tracing span so a shrink shows up on the merged timeline.

Gate: ``MXNET_ELASTIC=1`` + a shared ``MXNET_ELASTIC_DIR`` (tools/launch.py
``--restart-policy shrink`` sets both for every worker).
"""
from __future__ import annotations

import os
import socket
import sys
import threading
import time

from .. import analysis
from .. import health
from .. import telemetry
from .. import tracing
from ..base import getenv, register_env
from ..log import get_logger
from ..resilience import WorkerLostError

__all__ = ["ElasticRuntime", "WorkerLostError", "elastic_enabled",
           "active", "guard", "ensure_started", "generation",
           "shrink_and_exec", "runtime"]

register_env("MXNET_ELASTIC", False,
             "elastic dist runtime: heartbeat leases over the rendezvous, "
             "WorkerLostError from collectives instead of a hung barrier, "
             "shrink rendezvous + checkpoint resume on worker death")
register_env("MXNET_ELASTIC_DIR", "",
             "shared directory for heartbeat leases and the shrink "
             "rendezvous (must be visible to every worker; the launcher's "
             "--restart-policy shrink provisions it)")
register_env("MXNET_ELASTIC_HEARTBEAT_S", 0.5,
             "heartbeat lease renewal interval in seconds")
register_env("MXNET_ELASTIC_GRACE_S", 10.0,
             "a peer whose lease is older than this is declared lost; "
             "bounds how long a dead worker can stall the fleet")
register_env("MXNET_ELASTIC_GENERATION", 0,
             "current elastic generation (set by exec_resume across "
             "shrinks; generation 0 is the original launch)")


def elastic_enabled():
    return bool(getenv("MXNET_ELASTIC"))


def generation():
    """The process's elastic generation: 0 at first launch, +1 per shrink
    (resumed processes read it to decide to reload the checkpoint)."""
    return int(getenv("MXNET_ELASTIC_GENERATION") or 0)


def _logger():
    return get_logger("mxnet_tpu.elastic")


class Heartbeater(threading.Thread):
    """Daemon thread renewing this rank's lease file: an atomic replace of
    ``hb-<rank>`` containing ``<wall-time> <pid>`` every interval. Peers
    read the embedded timestamp (not mtime — clock-readable in tests and
    robust to filesystems with coarse mtimes)."""

    def __init__(self, path, interval_s):
        super().__init__(daemon=True, name="elastic-heartbeat")
        self.path = path
        self.interval_s = float(interval_s)
        self._stop = threading.Event()

    def beat_once(self):
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"{time.time()} {os.getpid()}")
        os.replace(tmp, self.path)
        if telemetry._enabled:
            telemetry.counter("elastic.heartbeats").inc()

    def run(self):
        while not self._stop.is_set():
            try:
                self.beat_once()
            except OSError as e:  # lease dir vanished — peers will notice
                _logger().warning("heartbeat write failed: %s", e)
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()


def _read_lease(path):
    """Lease timestamp in ``path``, or None when missing/torn."""
    try:
        with open(path) as f:
            return float(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return None


class ElasticRuntime:
    """One worker's view of the elastic fleet (rank/world of the CURRENT
    generation, lease dir, detector state). Normally a process singleton
    built from env (:func:`runtime`); tests construct instances directly.
    """

    def __init__(self, root, rank, world, gen=None, heartbeat_s=None,
                 grace_s=None):
        self.root = str(root)
        self.rank = int(rank)
        self.world = int(world)
        self.generation = generation() if gen is None else int(gen)
        self.heartbeat_s = float(getenv("MXNET_ELASTIC_HEARTBEAT_S")
                                 if heartbeat_s is None else heartbeat_s)
        self.grace_s = float(getenv("MXNET_ELASTIC_GRACE_S")
                             if grace_s is None else grace_s)
        self._heartbeater = None
        self._started_at = None
        self._lost = set()
        if telemetry._enabled:
            telemetry.gauge("elastic.generation").set(self.generation)
            telemetry.gauge("elastic.world_size").set(self.world)

    # -- lease plumbing ------------------------------------------------------

    def _gen_dir(self, gen=None):
        return os.path.join(self.root,
                            f"gen-{self.generation if gen is None else gen}")

    def _hb_path(self, rank, gen=None):
        return os.path.join(self._gen_dir(gen), f"hb-{rank}")

    def start(self):
        """Begin renewing this rank's lease (idempotent)."""
        if self._heartbeater is not None:
            return self
        os.makedirs(self._gen_dir(), exist_ok=True)
        self._started_at = time.time()
        self._heartbeater = Heartbeater(self._hb_path(self.rank),
                                        self.heartbeat_s)
        self._heartbeater.beat_once()
        self._heartbeater.start()
        return self

    def stop(self):
        if self._heartbeater is not None:
            self._heartbeater.stop()
            self._heartbeater = None

    def peer_ranks(self):
        return [r for r in range(self.world) if r != self.rank]

    def lost_peers(self):
        """Ranks whose lease expired (age > grace). A peer that never
        wrote a lease counts from this runtime's own start time — a
        worker that died before its first beat must still be detected."""
        now = time.time()
        base = self._started_at or now
        lost = []
        for r in self.peer_ranks():
            ts = _read_lease(self._hb_path(r))
            age = now - (ts if ts is not None else base)
            if age > self.grace_s:
                lost.append(r)
        for r in lost:
            if r not in self._lost:
                self._lost.add(r)
                if telemetry._enabled:
                    telemetry.counter("elastic.lost_workers").inc()
                if health._enabled:
                    health.event("worker_lost", rank=r, world=self.world,
                                 generation=self.generation)
                _logger().error(
                    "worker %d lost (lease expired > %.1fs) — fleet was "
                    "%d ranks, generation %d", r, self.grace_s, self.world,
                    self.generation)
        return lost

    def check(self, desc="collective"):
        """Raise :class:`WorkerLostError` if any peer's lease expired."""
        lost = self.lost_peers()
        if lost:
            raise WorkerLostError(desc, lost)

    # -- guarded collectives -------------------------------------------------

    def guard(self, fn, desc="collective"):
        """Run the (blocking) ``fn`` on a worker thread while polling the
        leases. Outcomes:

        * ``fn`` returns with every lease fresh → its result.
        * a peer's lease expires (before, during, or after a failure of
          ``fn``) → :class:`WorkerLostError`, chaining ``fn``'s own error
          when it raced the detection. The stuck daemon thread is
          abandoned — the caller is about to shrink+re-exec anyway.
        * ``fn`` raises with every lease fresh for a full grace window →
          the original error (a genuine collective failure, not a death).

        No fixed timeout: slow-but-alive fleets are never interrupted;
        the lease is the only unblock signal.
        """
        if self.world <= 1:
            return fn()
        box = {}
        done = threading.Event()

        def run():
            try:
                box["v"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["e"] = e
            finally:
                done.set()

        th = threading.Thread(target=run, daemon=True,
                              name=f"elastic-guard-{desc}")
        th.start()
        poll = min(self.heartbeat_s, 0.2)
        raised_at = None
        while True:
            finished = done.wait(poll)
            if finished and "e" not in box:
                return box["v"]
            lost = self.lost_peers()
            if lost:
                raise WorkerLostError(desc, lost, cause=box.get("e"))
            if finished:
                # the collective failed but everyone still looks alive:
                # give the leases one grace window to expose a death that
                # raced the error (a gloo connection reset lands before
                # the lease goes stale), then let the real error through.
                # done is already set, so done.wait returns immediately —
                # sleep the poll interval explicitly or this lap of the
                # window becomes a busy spin over the lease files
                if raised_at is None:
                    raised_at = time.monotonic()
                elif time.monotonic() - raised_at > self.grace_s:
                    raise box["e"]
                time.sleep(poll)

    # -- shrink rendezvous ---------------------------------------------------

    def shrink(self):
        """Agree on the surviving membership and the next generation's
        process-group spec. Every survivor calls this after
        :class:`WorkerLostError`; returns ``{"generation", "world",
        "rank", "coordinator"}`` (coordinator None when world == 1)."""
        t0 = time.perf_counter()
        with tracing.span("elastic.shrink", cat="dist",
                          generation=self.generation, rank=self.rank):
            spec = self._shrink()
        dt_us = (time.perf_counter() - t0) * 1e6
        if telemetry._enabled:
            telemetry.counter("elastic.shrinks").inc()
            telemetry.histogram("elastic.shrink_us").record(dt_us)
            telemetry.gauge("elastic.generation").set(spec["generation"])
            telemetry.gauge("elastic.world_size").set(spec["world"])
        if health._enabled:
            health.event("elastic_shrink", generation=spec["generation"],
                         world=spec["world"], rank=spec["rank"])
        _logger().warning(
            "shrink rendezvous complete in %.0f ms: generation %d -> %d, "
            "world %d -> %d, new rank %d, coordinator %s",
            dt_us / 1e3, self.generation, spec["generation"], self.world,
            spec["world"], spec["rank"], spec["coordinator"])
        return spec

    def _shrink(self):
        new_gen = self.generation + 1
        gendir = self._gen_dir(new_gen)
        os.makedirs(gendir, exist_ok=True)
        my_join = os.path.join(gendir, f"join-{self.rank}")
        with open(my_join, "w") as f:
            f.write(str(os.getpid()))
        poll = min(self.heartbeat_s, 0.2)
        deadline = time.monotonic() + self.grace_s + 2 * self.heartbeat_s
        while True:
            joined = {int(n.split("-", 1)[1])
                      for n in os.listdir(gendir) if n.startswith("join-")}
            lost = set(self.lost_peers())
            expected = ({self.rank} |
                        set(self.peer_ranks())) - lost
            if expected <= joined or time.monotonic() > deadline:
                break
            time.sleep(poll)
        # membership is ONE published decision, not a per-rank snapshot:
        # survivors detect the loss at different times, so private
        # `joined - lost` views can disagree (rank A re-execs as world 1
        # while rank B waits for a 2-worker coordinator that never
        # comes). The lowest-ranked joiner publishes the member list with
        # an O_EXCL create (first writer wins; the next candidate takes
        # over if the decider dies mid-shrink) and everyone adopts it.
        members_path = os.path.join(gendir, "members")
        read_deadline = time.monotonic() + self.grace_s
        members = None
        while True:
            try:
                with open(members_path) as f:
                    members = sorted(int(x) for x in f.read().split(",")
                                     if x.strip())
                break
            except OSError:
                pass
            joined = {int(n.split("-", 1)[1])
                      for n in os.listdir(gendir) if n.startswith("join-")}
            alive = sorted((joined | {self.rank}) - set(self.lost_peers()))
            if alive[0] == self.rank:
                try:
                    fd = os.open(members_path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    with os.fdopen(fd, "w") as f:
                        f.write(",".join(str(r) for r in alive))
                except FileExistsError:
                    pass  # someone else decided first — adopt theirs
                continue
            if time.monotonic() > read_deadline:
                # the decider never published (joined then died with its
                # lease not yet expired, or it has not noticed the death):
                # claim the decision OURSELVES through the same O_EXCL
                # gate and loop to adopt whatever actually landed — two
                # late survivors then read ONE file instead of silently
                # forking into independent fleets
                try:
                    fd = os.open(members_path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    with os.fdopen(fd, "w") as f:
                        f.write(",".join(str(r) for r in alive))
                except FileExistsError:
                    pass
                continue  # the file exists now; the next lap reads it
            time.sleep(poll)
        if self.rank not in members:
            # our join landed after the decision closed: we cannot be in
            # this generation. Fail loudly (the launcher's shrink policy
            # reports it) rather than split-brain into a private world.
            raise WorkerLostError(
                "shrink rendezvous", [],
                cause=RuntimeError(
                    f"generation {new_gen} membership {members} was "
                    f"published without rank {self.rank}"))
        new_world = len(members)
        new_rank = members.index(self.rank)
        coordinator = None
        if new_world > 1:
            coord_path = os.path.join(gendir, "coordinator")
            if new_rank == 0:
                with socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM) as s:
                    s.bind(("127.0.0.1", 0))
                    port = s.getsockname()[1]
                coordinator = f"127.0.0.1:{port}"
                tmp = coord_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(coordinator)
                os.replace(tmp, coord_path)
            else:
                wait_until = time.monotonic() + self.grace_s
                while time.monotonic() < wait_until:
                    try:
                        with open(coord_path) as f:
                            coordinator = f.read().strip()
                        break
                    except OSError:
                        time.sleep(min(self.heartbeat_s, 0.2))
                if coordinator is None:
                    raise WorkerLostError(
                        "shrink rendezvous", [members[0]],
                        cause=RuntimeError("new rank 0 never published a "
                                           "coordinator"))
        return {"generation": new_gen, "world": new_world,
                "rank": new_rank, "coordinator": coordinator}

    def exec_resume(self, spec):
        """Re-exec this process into the shrunk process group: update the
        rendezvous env (native + DMLC names) and replace the image with
        the same argv. The resumed process reads ``generation() > 0`` and
        continues from the latest good checkpoint. Does not return."""
        env = os.environ
        env["MXNET_ELASTIC_GENERATION"] = str(spec["generation"])
        env["MXNET_NUM_PROCESSES"] = str(spec["world"])
        env["MXNET_PROCESS_ID"] = str(spec["rank"])
        env["DMLC_NUM_WORKER"] = str(spec["world"])
        env["DMLC_WORKER_ID"] = str(spec["rank"])
        if spec["coordinator"]:
            env["MXNET_COORDINATOR"] = spec["coordinator"]
            host, _, port = spec["coordinator"].rpartition(":")
            env["DMLC_PS_ROOT_URI"] = host
            env["DMLC_PS_ROOT_PORT"] = port
        else:
            for k in ("MXNET_COORDINATOR", "DMLC_PS_ROOT_URI",
                      "DMLC_PS_ROOT_PORT"):
                env.pop(k, None)
        self.stop()
        _logger().warning(
            "re-exec into generation %d as rank %d/%d: %s",
            spec["generation"], spec["rank"], spec["world"],
            " ".join([sys.executable] + sys.argv))
        sys.stdout.flush()
        sys.stderr.flush()
        # NOTE: execv runs no atexit handlers — telemetry dumps and engine
        # flushes of this incarnation are intentionally abandoned; the
        # resumed image re-creates them
        os.execv(sys.executable, [sys.executable] + sys.argv)


# ---------------------------------------------------------------------------
# process singleton
# ---------------------------------------------------------------------------

_runtime = None
_runtime_lock = analysis.make_lock("elastic.runtime")


def runtime():
    """The env-configured runtime singleton (None when the gate is off or
    the fleet is degenerate: no shared dir, or world <= 1)."""
    global _runtime
    if _runtime is not None:
        return _runtime
    if not elastic_enabled():
        return None
    root = str(getenv("MXNET_ELASTIC_DIR") or "")
    world = int(os.environ.get("MXNET_NUM_PROCESSES",
                               os.environ.get("DMLC_NUM_WORKER", "1")))
    if not root or world <= 1:
        return None
    rank = int(os.environ.get("MXNET_PROCESS_ID",
                              os.environ.get("DMLC_WORKER_ID", "0")))
    with _runtime_lock:
        if _runtime is None:
            _runtime = ElasticRuntime(root, rank, world)
    return _runtime


def ensure_started():
    """Start the heartbeat lease if the elastic gate is on (idempotent;
    called from `dist.init_process_group` / `launcher.initialize_from_env`
    so every rendezvous path arms the detector)."""
    rt = runtime()
    if rt is not None:
        rt.start()
    return rt


def active():
    """Whether collectives should route through the guard: a started
    runtime with real peers."""
    rt = _runtime
    return rt is not None and rt._heartbeater is not None and rt.world > 1


def guard(fn, desc="collective"):
    """Route one blocking collective through the runtime's lease guard
    (identity when the runtime is inactive)."""
    rt = _runtime
    if rt is None or rt._heartbeater is None:
        return fn()
    return rt.guard(fn, desc=desc)


def shrink_and_exec():
    """Survivor path after :class:`WorkerLostError`: run the shrink
    rendezvous, then re-exec into the new process group. Does not return
    (raises only if the rendezvous itself collapses)."""
    rt = runtime()
    if rt is None:
        raise WorkerLostError("shrink", [], cause=RuntimeError(
            "elastic runtime not configured (MXNET_ELASTIC/_DIR)"))
    rt.start()
    spec = rt.shrink()
    rt.exec_resume(spec)
