"""Pipeline parallelism over the 'pp' mesh axis.

The reference has none (SURVEY.md §2.4 row "Pipeline parallelism: ❌").
TPU-native GPipe-style schedule: stages live on 'pp' shards, microbatches
stream through with `ppermute` handoffs inside one SPMD program — XLA
overlaps the per-stage compute with the boundary transfer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_step(stage_fn, params_stack, x_microbatches, axis_name, axis_size):
    """Run a GPipe forward inside `shard_map`.

    stage_fn(stage_params, h) -> h, applied by every device to the
    microbatch currently resident on it; `params_stack` is this device's
    stage parameters; `x_microbatches` [M, ...] local input microbatches
    (only stage 0's are consumed). Returns [M, ...] outputs valid on the
    LAST stage. M must be >= axis_size for full utilisation.
    """
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    n_ticks = m + axis_size - 1
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    h_shape = x_microbatches.shape[1:]
    # initial carry must carry the full varying-axes set up front (it picks
    # up pp-varying params and x's data-axes on the first tick; fori_loop
    # needs a fixed carry type): inherit x's axes via a zero of x, then add pp
    zero = x_microbatches[0] * 0
    if hasattr(lax, "pcast"):
        _pvary = lambda x, axes: lax.pcast(x, axes, to="varying")  # noqa: E731
    elif hasattr(lax, "pvary"):
        _pvary = lax.pvary
    else:  # older jax has no varying-axis tracking: the cast is a no-op
        _pvary = lambda x, axes: x  # noqa: E731
    state = _pvary(zero, (axis_name,))
    outputs = _pvary(jnp.broadcast_to(zero, (m,) + h_shape), (axis_name,))

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (when available)
        feed = jnp.where(t < m, 1, 0)
        mb = x_microbatches[jnp.minimum(t, m - 1)]
        state = jnp.where((idx == 0) & (feed == 1), mb, state)
        state = stage_fn(params_stack, state)
        # last stage emits result for microbatch t - (axis_size - 1)
        out_t = t - (axis_size - 1)
        valid = (idx == axis_size - 1) & (out_t >= 0)
        updated = outputs.at[jnp.maximum(out_t, 0)].set(state)
        outputs = jnp.where(valid, updated, outputs)
        # hand off to next stage
        state = lax.ppermute(state, axis_name, perm)
        return (state, outputs), None

    # lax.scan (not fori_loop): the tick loop must be REVERSE-differentiable
    # so pipeline training steps can backprop through the schedule
    (_, outputs), _ = lax.scan(tick, (state, outputs), jnp.arange(n_ticks))
    # results live on the last stage only; broadcast to every stage so the
    # output is replicated over 'pp' (a masked psum = one-to-all over ICI)
    outputs = lax.psum(jnp.where(idx == axis_size - 1, outputs, 0 * outputs),
                       axis_name)
    return outputs
