"""Pipeline-parallel training over the 'pp' mesh axis (GPipe schedule).

The reference has none (SURVEY.md §2.4 row "Pipeline parallelism: ❌").
This module grows the original forward-only demo (`pipeline_step`, kept
below) into a real training subsystem:

* :func:`partition_stages` cuts the Symbol graph into ``S`` contiguous
  stages balanced by parameter + activation weight (the linear-partition
  DP), and derives the cut boundaries — every intermediate value that
  crosses a cut rides the inter-stage handoff buffer.
* :class:`PipelineContext` compiles the GPipe micro-batch schedule into
  the donated-buffer fused train step (`Executor.fused_step`): the batch
  is split into ``M`` micro-batches, ``M + S - 1`` `lax.scan` ticks run
  one stage per device of the 'pp' mesh axis (`lax.switch` on
  `axis_index` selects the stage subgraph), activations hand off with
  `lax.ppermute` (one ICI hop on a TPU torus), and `jax.vjp` through the
  schedule produces the reverse pipeline flow — gradients accumulate
  across micro-batches inside the ONE jitted computation, then feed the
  same grad-sync / ZeRO-1 / optimizer tail as the unpipelined step.

Bubble accounting: the schedule idles (S-1)/(M+S-1) of its device-ticks
(`pipeline.bubble_ratio` gauge) — raise `MXNET_PIPELINE_MICROBATCHES` to
amortize (docs/faq/perf.md "Choosing micro-batch count").

Numerics: micro-batching is exact for batch-separable graphs (per-row
losses; the SoftmaxOutput default). Graphs that mix rows across the batch
fall back to the unpipelined fused step: auxiliary (running-stat) states
(BatchNorm), `normalization='batch'/'valid'` loss heads, outputs without
a leading batch dim, and non-float cut boundaries are all detected at
plan time (`PipelineFallback`). A short trailing micro-batch is padded
with recycled rows and masked exactly through the output slice's vjp.

Gate: `MXNET_PIPELINE_STAGES` (0 = off) / `MXNET_PIPELINE_MICROBATCHES`
(0 = 2x stages).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry
from ..base import getenv, register_env
from . import mesh as mesh_mod
from .collectives import shard_map

__all__ = ["pipeline_step", "partition_stages", "PipelineContext",
           "PipelineFallback", "pipeline_enabled", "StagePlan"]

register_env("MXNET_PIPELINE_STAGES", 0,
             "pipeline-parallel stage count for the fused train step "
             "(GPipe micro-batch schedule over the 'pp' mesh axis); "
             "0 = off. Graphs the schedule cannot split exactly fall "
             "back to the unpipelined fused step")
register_env("MXNET_PIPELINE_MICROBATCHES", 0,
             "micro-batches per step for the pipeline schedule; 0 = "
             "2x MXNET_PIPELINE_STAGES. Bubble fraction is "
             "(S-1)/(M+S-1) — see docs/faq/perf.md")


def pipeline_enabled():
    return int(getenv("MXNET_PIPELINE_STAGES") or 0) >= 2


class PipelineFallback(Exception):
    """The graph (or environment) cannot run the pipeline schedule; the
    caller should use the unpipelined fused step. Carries the reason —
    Module logs it once."""


def _pvary(x, axes):
    """Varying-axis cast across jax versions (pcast / pvary / no-op)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


# ---------------------------------------------------------------------------
# Stage partition
# ---------------------------------------------------------------------------

class _BoundaryVal:
    """One tensor crossing a stage cut: (producer node, output index) plus
    its micro-batch-scale shape/dtype and flat span in the handoff buffer."""

    __slots__ = ("nid", "oi", "shape", "dtype", "size", "offset")

    def __init__(self, nid, oi, shape, dtype, offset):
        self.nid = nid
        self.oi = int(oi)
        self.shape = tuple(int(s) for s in shape)
        self.dtype = jnp.dtype(dtype)
        self.size = int(np.prod(self.shape)) if self.shape else 1
        self.offset = int(offset)

    def sig(self):
        return (self.shape, str(self.dtype), self.offset)


class StagePlan:
    """Static pipeline layout: topo-contiguous stage node lists, per-cut
    boundary layouts, micro-batch-scale output specs, and the balance
    telemetry the partition DP produced."""

    def __init__(self, stages, stage_costs, boundaries, out_specs,
                 node_index, var_ids, max_flat):
        self.stages = tuple(tuple(s) for s in stages)
        self.stage_costs = tuple(float(c) for c in stage_costs)
        self.boundaries = tuple(tuple(b) for b in boundaries)
        self.out_specs = tuple(out_specs)  # [(shape(mb,...), dtype)]
        self.node_index = node_index       # id(node) -> global topo index
        self.var_ids = var_ids             # arg name -> id(var node)
        self.max_flat = int(max_flat)

    @property
    def num_stages(self):
        return len(self.stages)

    def sig(self):
        """Hashable layout identity (compile-cache key component)."""
        return (tuple(len(s) for s in self.stages),
                tuple(tuple(v.sig() for v in b) for b in self.boundaries),
                tuple((s, str(d)) for s, d in self.out_specs),
                self.max_flat)


def _balanced_cuts(costs, num_stages):
    """Linear-partition DP: split ``costs`` into ``num_stages`` contiguous
    non-empty segments minimizing the max segment sum. Returns segment
    start indices (first is 0)."""
    n = len(costs)
    prefix = np.concatenate([[0.0], np.cumsum(np.asarray(costs, np.float64))])

    def seg(i, j):  # cost of items [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[k][j]: minimal max-cost of splitting first j items into k parts
    best = [[INF] * (n + 1) for _ in range(num_stages + 1)]
    cut = [[0] * (n + 1) for _ in range(num_stages + 1)]
    best[0][0] = 0.0
    for k in range(1, num_stages + 1):
        for j in range(k, n - (num_stages - k) + 1):
            for i in range(k - 1, j):
                c = max(best[k - 1][i], seg(i, j))
                if c < best[k][j]:
                    best[k][j] = c
                    cut[k][j] = i
    starts = []
    j = n
    for k in range(num_stages, 0, -1):
        i = cut[k][j]
        starts.append(i)
        j = i
    return list(reversed(starts))


# cross-micro-batch loss normalizations: backward divides by the TRACED
# batch dim, which is the micro-batch under this schedule — not separable
_BATCH_NORMALIZATIONS = ("batch", "valid")


def partition_stages(symbol, num_stages, input_specs, batch_names=()):
    """Cut ``symbol`` into ``num_stages`` balanced contiguous stages.

    ``input_specs``: {arg name: (shape, dtype)} at MICRO-batch scale —
    batch inputs already sized to one micro-batch. ``batch_names``: the
    data/label inputs (excluded from the parameter-weight cost term).

    Raises :class:`PipelineFallback` for graphs the schedule cannot run
    exactly; see the module docstring for the trigger list.
    """
    from ..symbol.symbol import _topo_order

    S = int(num_stages)
    if S < 2:
        raise PipelineFallback(f"need >= 2 stages, got {S}")
    if symbol.list_auxiliary_states():
        raise PipelineFallback(
            "graph has auxiliary (running-stat) states; per-micro-batch "
            "aux chaining is not batch-separable")
    nodes = _topo_order([n for n, _ in symbol._outputs])
    compute = [n for n in nodes if not n.is_variable]
    if len(compute) < S:
        raise PipelineFallback(
            f"{len(compute)} compute nodes cannot fill {S} stages")
    for n in compute:
        if str(n.attrs.get("normalization", "null")) in _BATCH_NORMALIZATIONS:
            raise PipelineFallback(
                f"{n.op} normalization={n.attrs['normalization']!r} "
                "divides by the traced batch dim (not micro-batch "
                "separable)")
    node_index = {id(n): i for i, n in enumerate(nodes)}
    var_ids = {}
    for n in nodes:
        if n.is_variable:
            if n.name not in input_specs:
                raise PipelineFallback(f"no bound spec for input {n.name!r}")
            var_ids[n.name] = id(n)

    # abstract eval of every compute value at micro-batch scale: shapes
    # AND dtypes, without running math (jax.eval_shape over the same walk
    # the stage branches run)
    entries = []
    for n in compute:
        for i in range(n.num_outputs()):
            entries.append((n, i))

    names = list(input_specs)

    def probe(key, *args):
        env = {}
        for nm, a in zip(names, args):
            env[(var_ids[nm], 0)] = a
        _walk_nodes(compute, env, key, True, node_index)
        return tuple(env[(id(n), i)] for n, i in entries)

    arg_specs = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                 for s, d in (input_specs[nm] for nm in names)]
    try:
        out = jax.eval_shape(probe, jax.random.PRNGKey(0), *arg_specs)
    except Exception as e:  # noqa: BLE001 — any abstract-eval failure
        raise PipelineFallback(f"graph abstract eval failed: {e!r}") from e
    val_info = {(id(n), i): (tuple(sd.shape), jnp.dtype(sd.dtype))
                for (n, i), sd in zip(entries, out)}

    # cost model: parameter elements this node owns (its variable inputs
    # that are not data/label feeds) + its output activation elements —
    # the same weight/FLOP proxy the GPipe paper balances on
    batch_set = set(batch_names)
    costs = []
    for n in compute:
        c = 0.0
        for child, _oi in n.inputs:
            if child.is_variable and child.name not in batch_set:
                shape = input_specs[child.name][0]
                c += float(np.prod(shape)) if shape else 1.0
        for i in range(n.num_outputs()):
            c += float(np.prod(val_info[(id(n), i)][0]) or 1.0)
        costs.append(c)

    starts = _balanced_cuts(costs, S)
    bounds = starts[1:] + [len(compute)]
    stages = [compute[a:b] for a, b in zip(starts, bounds)]
    stage_costs = [sum(costs[a:b]) for a, b in zip(starts, bounds)]
    stage_of = {}
    for s, stg in enumerate(stages):
        for n in stg:
            stage_of[id(n)] = s

    # need_beyond[(nid, oi)]: the deepest stage that consumes this value
    # (graph outputs must reach the last stage)
    need_beyond = {}
    for s, stg in enumerate(stages):
        for n in stg:
            for child, oi in n.inputs:
                if not child.is_variable:
                    k = (id(child), oi)
                    need_beyond[k] = max(need_beyond.get(k, -1), s)
    for n, oi in symbol._outputs:
        if not n.is_variable:
            need_beyond[(id(n), oi)] = S - 1

    boundaries = []
    max_flat = 0
    for c in range(S - 1):
        layout = []
        off = 0
        for n, oi in entries:
            if stage_of[id(n)] <= c and need_beyond.get((id(n), oi), -1) > c:
                shape, dtype = val_info[(id(n), oi)]
                if not jnp.issubdtype(dtype, jnp.floating):
                    raise PipelineFallback(
                        f"cut {c} carries non-float value "
                        f"{n.name}:{oi} ({dtype}); the f32 handoff "
                        "buffer cannot round-trip it")
                bv = _BoundaryVal(id(n), oi, shape, dtype, off)
                off += bv.size
                layout.append(bv)
        if not layout:
            raise PipelineFallback(
                f"cut {c} carries no values (disconnected stages)")
        max_flat = max(max_flat, off)
        boundaries.append(layout)

    out_specs = []
    for n, oi in symbol._outputs:
        if n.is_variable:
            shape, dtype = input_specs[n.name]
            shape, dtype = tuple(shape), jnp.dtype(dtype)
        else:
            shape, dtype = val_info[(id(n), oi)]
        out_specs.append((shape, dtype))
    return StagePlan(stages, stage_costs, boundaries, out_specs,
                     node_index, var_ids, max_flat)


def _walk_nodes(nodes, env, key, train, node_index, loss_gate=None):
    """Evaluate a topo-ordered node subset into ``env`` — the executor's
    per-node dispatch (`symbol.executor._dispatch_node`, ONE home for the
    op-dispatch convention) restricted to one stage; ``node_index`` keys
    the RNG fold by GLOBAL topo index so stage splits never change which
    key a random op sees.

    ``loss_gate``: optional ``(node_id_set, fn)`` applying ``fn`` to the
    inputs of the named nodes — the pipeline's per-row pad mask on the
    graph-output (loss) nodes, whose custom vjps may emit gradients
    regardless of the incoming cotangent."""
    from ..symbol.executor import _dispatch_node

    for node in nodes:
        if node.is_variable:
            continue
        gate = loss_gate[1] if loss_gate is not None and \
            id(node) in loss_gate[0] else None
        _dispatch_node(node, env, key, train, node_index[id(node)],
                       gate=gate)


# ---------------------------------------------------------------------------
# The traced GPipe schedule
# ---------------------------------------------------------------------------

def _resolve_mesh(num_stages):
    """The 'pp' shard group: the ambient/env mesh when it carries a pp
    axis of the right size (so `MXNET_MESH_SHAPE='dp=2,pp=2'` composes),
    else a fresh 1-D pp mesh over the first S devices."""
    for m in (mesh_mod.current_mesh(), mesh_mod.mesh_from_env()):
        if m is not None and \
                mesh_mod.axis_size(m, mesh_mod.AXIS_PP) == num_stages:
            return m
    devices = jax.devices()
    if num_stages > len(devices):
        raise PipelineFallback(
            f"{num_stages} pipeline stages but only {len(devices)} devices")
    return mesh_mod.pp_mesh(num_stages)


class PipelineContext:
    """One module's pipeline schedule: the stage plan, the pp mesh, and
    the traced GPipe forward the fused step consumes in place of the
    plain graph function. Owned by `Module` (like `Zero1Context`); a
    plan/trace failure falls back to the unpipelined fused step."""

    def __init__(self, symbol, plan, batch_size, microbatches, batch_names,
                 mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.symbol = symbol
        self.plan = plan
        self.batch_size = int(batch_size)
        self.microbatches = int(microbatches)
        self.batch_names = tuple(batch_names)
        self.mesh = mesh
        self.axis = mesh_mod.AXIS_PP
        self.mb = -(-self.batch_size // self.microbatches)  # ceil
        self.pad = self.mb * self.microbatches - self.batch_size
        self.repl = NamedSharding(mesh, P())
        # named CompileCache so `compile_cache.named_stats('pipeline')`
        # pins one compile per (symbol, shapes, stages, microbatches)
        # config — but PER CONTEXT, not process-global: the cached jitted
        # step closes over the executor, so a global cache would pin every
        # module it ever served (weights, multi-device buffers, census
        # providers) alive for the process lifetime, and donated entries
        # make every /memory scrape that walks live caches re-pay their
        # AOT analysis. The monotonic named totals still aggregate across
        # contexts, so accounting assertions survive the cache's death.
        from ..compile_cache import CompileCache

        self.cache = CompileCache("pipeline", maxsize=8)
        import zlib

        self._sym_crc = zlib.crc32(symbol.tojson().encode())
        # the schedule's shard_map is manual over EVERY mesh axis but only
        # 'pp' differentiates the work: compute replicates across the
        # other axes, and the vjp transpose SUMS those identical
        # per-coordinate cotangent contributions — gradients come back
        # scaled by the product of the extra axis sizes. The fused step
        # divides this back out (exact for power-of-2 meshes). Latent on
        # pure-pp meshes (factor 1); real for the documented
        # MXNET_MESH_SHAPE='dp=2,pp=2' composition and every MXNET_SPMD
        # mesh carrying pp beside fsdp/tp.
        self.grad_correction = 1
        for ax, sz in mesh.shape.items():
            if ax != self.axis:
                self.grad_correction *= int(sz)
        s, m = plan.num_stages, self.microbatches
        self.bubble_ratio = (s - 1) / (m + s - 1)
        costs = plan.stage_costs
        self.stage_cost_imbalance = \
            max(costs) / max(sum(costs) / len(costs), 1e-12)

    def record_step(self):
        """Per-step telemetry (called by `Executor.fused_step` after a
        successful pipelined dispatch). The config gauges are re-set here
        rather than once at construction so telemetry enabled mid-run
        still reports stages/micro-batches/bubble next to the counter."""
        if not telemetry._enabled:
            return
        telemetry.counter("pipeline.steps").inc()
        telemetry.gauge("pipeline.stages").set(self.plan.num_stages)
        telemetry.gauge("pipeline.microbatches").set(self.microbatches)
        telemetry.gauge("pipeline.bubble_ratio").set(self.bubble_ratio)
        telemetry.gauge("pipeline.stage_cost_imbalance").set(
            self.stage_cost_imbalance)

    # -- construction --------------------------------------------------------

    @staticmethod
    def build(symbol, executor, data_names, label_names, mesh=None):
        """Plan the schedule for a bound executor, or raise
        :class:`PipelineFallback` with the reason. ``mesh``: an explicit
        mesh carrying the 'pp' axis (the SPMD context's one-mesh
        composition — `Module` passes `spmd.mesh` so the schedule and
        the sharding plan live on the SAME device assignment)."""
        S = int(getenv("MXNET_PIPELINE_STAGES") or 0)
        M = int(getenv("MXNET_PIPELINE_MICROBATCHES") or 0) or 2 * S
        batch_names = tuple(n for n in list(data_names) + list(label_names)
                            if n in executor.arg_dict)
        if not batch_names:
            raise PipelineFallback("no bound batch inputs")
        B = int(executor.arg_dict[batch_names[0]].shape[0])
        if M > B:
            raise PipelineFallback(
                f"{M} micro-batches but only {B} batch rows")
        if mesh is not None:
            if mesh_mod.axis_size(mesh, mesh_mod.AXIS_PP) != S:
                raise PipelineFallback(
                    f"explicit mesh {dict(mesh.shape)} does not carry a "
                    f"'pp' axis of size {S}")
        else:
            mesh = _resolve_mesh(S)
        mb = -(-B // M)
        input_specs = {}
        for n in executor._arg_names:
            a = executor.arg_dict[n]
            shape = tuple(a.shape)
            if n in batch_names:
                if not shape or shape[0] != B:
                    raise PipelineFallback(
                        f"batch input {n!r} leading dim {shape} != {B}")
                shape = (mb,) + shape[1:]
            input_specs[n] = (shape, jnp.dtype(a.dtype))
        plan = partition_stages(symbol, S, input_specs,
                                batch_names=batch_names)
        for shape, _ in plan.out_specs:
            if not shape or shape[0] != mb:
                raise PipelineFallback(
                    f"output shape {shape} has no leading batch dim; "
                    "micro-batch results cannot be concatenated")
        ctx = PipelineContext(symbol, plan, B, M, batch_names, mesh)
        ctx._bound_sig = PipelineContext._exec_sig(executor)
        return ctx

    @staticmethod
    def _exec_sig(executor):
        return tuple((n, tuple(executor.arg_dict[n].shape),
                      str(executor.arg_dict[n].dtype))
                     for n in executor._arg_names)

    def matches(self, executor):
        """Whether this context still fits the executor's bound layout and
        the current env config. The FULL arg signature is compared — a
        reshape that keeps the batch dim but changes feature shapes would
        otherwise reuse a stale plan, fail its trace, and permanently
        disable pipelining for the module."""
        S = int(getenv("MXNET_PIPELINE_STAGES") or 0)
        M = int(getenv("MXNET_PIPELINE_MICROBATCHES") or 0) or 2 * S
        if (S, M) != (self.plan.num_stages, self.microbatches):
            return False
        try:
            return PipelineContext._exec_sig(executor) == self._bound_sig
        except KeyError:
            return False

    def key(self):
        """Compile-cache key component: everything that changes the traced
        schedule's layout."""
        return ("pipeline", self.plan.num_stages, self.microbatches,
                self.batch_size, self._sym_crc,
                mesh_mod.devices_key(self.mesh), self.plan.sig())

    def put_replicated(self, x):
        """Commit one fused-step input onto the pp mesh, replicated (the
        `Zero1Context.put_replicated` contract: steady state is a no-op
        for weights/state, per-step feeds broadcast once)."""
        arr = x if isinstance(x, jax.Array) or not hasattr(x, "_data") \
            else x._data
        try:
            if getattr(arr, "sharding", None) == self.repl:
                return arr
        except Exception:  # noqa: BLE001 — fall through to device_put
            pass
        return jax.device_put(arr, self.repl)

    # -- the traced forward --------------------------------------------------

    def wrap(self, executor, spmd=None):
        """The pipelined graph function with `Executor._fn(True)`'s
        contract — ``fn(key, args, auxs) -> (outputs, aux_updates)`` — so
        `Executor.fused_step` vjps and composes grad-sync/ZeRO-1/optimizer
        around it unchanged.

        ``spmd`` (a ``parallel.spmd.SpmdContext`` in pipeline mode):
        placed parameters ENTER the shard_map at their residency specs
        (each device holds 1/S of the parameter bytes between steps)
        and are all-gathered just-in-time at the top of the traced
        schedule — ``lax.all_gather``'s transpose reduce-scatters the
        accumulated micro-batch gradients straight back to the owning
        shards. Inside the schedule every mesh axis is manual, so this
        is residency placement, not propagated compute sharding."""
        from jax.sharding import PartitionSpec as P

        plan = self.plan
        S, M, mb, B, pad = (plan.num_stages, self.microbatches, self.mb,
                            self.batch_size, self.pad)
        axis = self.axis
        arg_names = list(executor._arg_names)
        batch_pos = frozenset(i for i, n in enumerate(arg_names)
                              if n in self.batch_names)
        out_entries = list(self.symbol._outputs)
        out_specs = plan.out_specs
        out_node_ids = frozenset(id(n) for n, _ in out_entries
                                 if not n.is_variable)
        perm = [(i, (i + 1) % S) for i in range(S)]
        max_flat = plan.max_flat
        # residency-placed params (SPMD composition): arg position ->
        # PartitionSpec; gathered once per step at the top of the traced
        # schedule, NOT per tick (the scan closes over the gathered value)
        placed = {}
        if spmd is not None:
            for pos, nm in enumerate(arg_names):
                spec = spmd.pp_spec(nm)
                if spec is not None and pos not in batch_pos:
                    placed[pos] = spec

        def _gather_full(x, spec):
            for d, ax in enumerate(tuple(spec)):
                if ax is not None:
                    x = lax.all_gather(x, ax, axis=d, tiled=True)
            return x

        def sched(key, *args):
            if placed:
                args = list(args)
                for pos, spec in placed.items():
                    args[pos] = _gather_full(args[pos], spec)
                args = tuple(args)
            idx = lax.axis_index(axis)

            def make_branch(si):
                stage_nodes = plan.stages[si]
                lin = plan.boundaries[si - 1] if si > 0 else ()
                lout = plan.boundaries[si] if si < S - 1 else ()

                def branch(operand):
                    state, t = operand
                    # stage si processes micro-batch t - si at tick t
                    mb_idx = jnp.clip(t - si, 0, M - 1)
                    # bubble-tick gate: every FLOAT input of the stage is
                    # scaled by 1.0 (active — bitwise identity) or 0.0
                    # (bubble). Masking only the OUTPUTS is not enough:
                    # loss-layer custom vjps (SoftmaxOutput) emit their
                    # gradient regardless of the incoming cotangent, so a
                    # warm-up tick would inject (p - onehot) into this
                    # stage's parameters; gating the inputs scales every
                    # such injection to exactly zero through the chain
                    # rule while leaving active ticks bit-identical.
                    act = ((t - si >= 0) & (t - si < M))

                    def gate(x):
                        if not jnp.issubdtype(x.dtype, jnp.floating):
                            return x  # no grad path through int inputs
                        return x * act.astype(x.dtype)

                    env = {}
                    for pos, nm in enumerate(arg_names):
                        a = args[pos]
                        env[(plan.var_ids[nm], 0)] = \
                            gate(a[mb_idx] if pos in batch_pos else a)
                    for bv in lin:
                        env[(bv.nid, bv.oi)] = gate(state[
                            bv.offset:bv.offset + bv.size].reshape(
                            bv.shape).astype(bv.dtype))
                    loss_gate = None
                    if pad:
                        # last micro-batch carries recycled pad rows whose
                        # outputs the [:B] slice discards — but a loss
                        # node's custom vjp ignores its cotangent, so the
                        # pad rows must be row-masked at the loss INPUTS
                        # (everything upstream then scales to zero; real
                        # rows multiply by exactly 1.0)
                        rowmask = (mb_idx * mb + jnp.arange(mb)) < B

                        def row_gate(x):
                            if not (hasattr(x, "ndim") and x.ndim >= 1
                                    and x.shape[0] == mb
                                    and jnp.issubdtype(x.dtype,
                                                       jnp.floating)):
                                return x
                            return x * rowmask.astype(x.dtype).reshape(
                                (mb,) + (1,) * (x.ndim - 1))

                        loss_gate = (out_node_ids, row_gate)
                    skey = jax.random.fold_in(key, mb_idx)
                    _walk_nodes(stage_nodes, env, skey, True,
                                plan.node_index, loss_gate=loss_gate)
                    if si == S - 1:
                        outs_t = tuple(env[(id(n), oi)]
                                       for n, oi in out_entries)
                        flat = jnp.zeros((max_flat,), jnp.float32)
                    else:
                        parts = [env[(bv.nid, bv.oi)].reshape(-1).astype(
                            jnp.float32) for bv in lout]
                        flat = parts[0] if len(parts) == 1 \
                            else jnp.concatenate(parts)
                        if flat.shape[0] < max_flat:
                            flat = jnp.pad(flat,
                                           (0, max_flat - flat.shape[0]))
                        outs_t = tuple(jnp.zeros(shape, dtype)
                                       for shape, dtype in out_specs)
                    return _pvary(flat, (axis,)), \
                        tuple(_pvary(o, (axis,)) for o in outs_t)

                return branch

            branches = [make_branch(i) for i in range(S)]
            state0 = _pvary(jnp.zeros((max_flat,), jnp.float32), (axis,))
            outs0 = tuple(_pvary(jnp.zeros((M,) + shape, dtype), (axis,))
                          for shape, dtype in out_specs)

            def tick(carry, t):
                state, outs = carry
                flat, outs_t = lax.switch(idx, branches, (state, t))
                # the last stage emits micro-batch t-(S-1)'s results
                out_t = t - (S - 1)
                valid = (idx == S - 1) & (out_t >= 0)
                new_outs = []
                for o, ot in zip(outs, outs_t):
                    upd = o.at[jnp.maximum(out_t, 0)].set(ot)
                    new_outs.append(jnp.where(valid, upd, o))
                # hand the activation buffer to the next stage — the
                # transpose of this ppermute IS the backward pipeline flow
                state = lax.ppermute(flat, axis, perm)
                return (state, tuple(new_outs)), None

            # lax.scan (reverse-differentiable): vjp through the tick loop
            # replays the schedule backward, accumulating per-stage grads
            (_, outs), _ = lax.scan(tick, (state0, outs0),
                                    jnp.arange(M + S - 1))
            # results live on the last stage only; the masked psum
            # broadcasts them over 'pp' (its transpose routes output
            # cotangents back to the emitting stage)
            return tuple(lax.psum(jnp.where(idx == S - 1, o, 0 * o), axis)
                         for o in outs)

        in_specs = (P(),) + tuple(placed.get(i, P())
                                  for i in range(len(arg_names)))
        fn = shard_map(sched, mesh=self.mesh,
                       in_specs=in_specs,
                       out_specs=tuple(P() for _ in out_entries),
                       check_vma=False)

        def pipelined(key, args, auxs):
            del auxs  # aux-state graphs fall back at plan time
            feed = list(args)
            for pos in batch_pos:
                a = feed[pos]
                if pad:
                    # recycle leading rows (real data, so inactive-tick
                    # compute stays finite); the [:B] slice below masks
                    # their cotangents to exactly zero through the vjp
                    a = jnp.concatenate([a, a[:pad]], axis=0)
                feed[pos] = a.reshape((M, mb) + tuple(a.shape[1:]))
            outs = fn(key, *feed)
            outs = tuple(o.reshape((M * mb,) + tuple(o.shape[2:]))[:B]
                         for o in outs)
            return outs, ()

        return pipelined


# ---------------------------------------------------------------------------
# Forward-only demo schedule (the original stub API; test_parallel.py)
# ---------------------------------------------------------------------------

def pipeline_step(stage_fn, params_stack, x_microbatches, axis_name, axis_size):
    """Run a GPipe forward inside `shard_map`.

    stage_fn(stage_params, h) -> h, applied by every device to the
    microbatch currently resident on it; `params_stack` is this device's
    stage parameters; `x_microbatches` [M, ...] local input microbatches
    (only stage 0's are consumed). Returns [M, ...] outputs valid on the
    LAST stage. M must be >= axis_size for full utilisation.
    """
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    n_ticks = m + axis_size - 1
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    h_shape = x_microbatches.shape[1:]
    # initial carry must carry the full varying-axes set up front (it picks
    # up pp-varying params and x's data-axes on the first tick; fori_loop
    # needs a fixed carry type): inherit x's axes via a zero of x, then add pp
    zero = x_microbatches[0] * 0
    state = _pvary(zero, (axis_name,))
    outputs = _pvary(jnp.broadcast_to(zero, (m,) + h_shape), (axis_name,))

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (when available)
        feed = jnp.where(t < m, 1, 0)
        mb = x_microbatches[jnp.minimum(t, m - 1)]
        state = jnp.where((idx == 0) & (feed == 1), mb, state)
        state = stage_fn(params_stack, state)
        # last stage emits result for microbatch t - (axis_size - 1)
        out_t = t - (axis_size - 1)
        valid = (idx == axis_size - 1) & (out_t >= 0)
        updated = outputs.at[jnp.maximum(out_t, 0)].set(state)
        outputs = jnp.where(valid, updated, outputs)
        # hand off to next stage
        state = lax.ppermute(state, axis_name, perm)
        return (state, outputs), None

    # lax.scan (not fori_loop): the tick loop must be REVERSE-differentiable
    # so pipeline training steps can backprop through the schedule
    (_, outputs), _ = lax.scan(tick, (state, outputs), jnp.arange(n_ticks))
    # results live on the last stage only; broadcast to every stage so the
    # output is replicated over 'pp' (a masked psum = one-to-all over ICI)
    outputs = lax.psum(jnp.where(idx == axis_size - 1, outputs, 0 * outputs),
                       axis_name)
    return outputs
