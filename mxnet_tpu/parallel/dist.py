"""Multi-host process group + the `dist_tpu_sync` KVStore.

Replaces ps-lite entirely (SURVEY.md §5): the reference runs a scheduler +
N server processes + M workers over ZMQ (`kvstore_dist.h:44`,
`kvstore_dist_server.h:155`), shards big keys across servers
(`EncodeDefaultKey:533`), and applies the optimizer server-side
(`ApplyUpdates:346`). On TPU there are no servers: every host joins one
SPMD process group (`jax.distributed`), arrays are global, and a push is an
AllReduce over ICI (DCN across slices) inside a tiny jitted program.
update_on_kvstore maps to an updater applied on the replicated aggregate —
identical math on every process, no server round-trip.

Data plane design (round-4 rewrite — no host bounce):

* values stay jax Arrays end-to-end; a push builds one **global** array
  whose leading axis is the device count (this process's contribution on
  its local device 0, zeros elsewhere — `make_array_from_single_device_arrays`,
  no host numpy copies), then runs one cached jitted ``sum(axis=0)`` with a
  fully-replicated output sharding: XLA lowers that to the AllReduce.
* keys are **bucketed**: one flattened+concatenated buffer per dtype per
  push call (cap `MXNET_KVSTORE_DIST_BUCKET_SIZE` elements), one collective
  per bucket — the reference's key batching (`MXNET_UPDATE_AGGREGATION_SIZE`,
  `kvstore_nccl.h`).
* 2-bit gradient compression (`gradient_compression.cc:45`): each worker
  quantizes with its own error-feedback residual, the packed uint32 words
  (16× smaller) ride one all-gather, and a single fused program dequantizes
  every worker's words and sums them (`..gradient_compression`).
* row_sparse pushes ship (indices, rows) padded to the max worker count —
  an all-gather of the occupied rows only; the full dense gradient is never
  materialized (reference `EncodeRowSparseKey`, `kvstore_dist.h:676`).
"""
from __future__ import annotations

import functools
import os
import time as _time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import default_mesh
from .. import telemetry
from ..base import getenv, register_env
from ..compile_cache import CompileCache
from ..kvstore import KVStoreBase
from . import collectives as coll

register_env("MXNET_UPDATE_AGGREGATION_SIZE", 0,
             "max KEYS fused into one dist-push collective bucket (the "
             "reference's update aggregation, kvstore_nccl.h); 0 = no "
             "key cap, element-size capping only")

# the in-store collective programs (sum/gather/fused-dequant), named so
# `named_stats("dist")` attributes wire recompiles (were anonymous
# lru_caches — the class tpulint's executable-cache rule now flags).
# track_memory=False: one tiny program per bucket layout — the /memory
# scrape's per-entry AOT analysis would re-pay a compile each
_dist_cache = CompileCache("dist", track_memory=False)

_initialized = False


def init_process_group(coordinator=None, num_processes=None, process_id=None):
    """Initialise jax.distributed from args or env (no-op single process).

    Env rendezvous keeps the reference's names working where they map:
    `DMLC_PS_ROOT_URI`/`DMLC_PS_ROOT_PORT` → coordinator address,
    `DMLC_NUM_WORKER` → process count, `DMLC_WORKER_ID` → process id
    (ps-lite's scheduler rendezvous, minus the scheduler).

    `MXNET_DIST_PLATFORM=cpu` (set by `tools/launch.py --launcher local`)
    forces the CPU backend with gloo cross-process collectives *before* the
    backend initialises — multi-worker correctness runs need no TPU.
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or _env_coordinator()
    if coordinator is None:
        _initialized = True  # single-process
        return
    platform = os.environ.get("MXNET_DIST_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    num_processes = num_processes or int(
        os.environ.get("MXNET_NUM_PROCESSES", os.environ.get("DMLC_NUM_WORKER", "1")))
    if process_id is None:
        process_id = int(
            os.environ.get("MXNET_PROCESS_ID", os.environ.get("DMLC_WORKER_ID", "0")))
    kwargs = dict(coordinator_address=coordinator,
                  num_processes=num_processes, process_id=process_id)
    # bounded rendezvous: without a timeout a worker whose coordinator died
    # (or whose fleet never fully launched) hangs forever with no hint
    from ..base import getenv

    timeout_s = int(getenv("MXNET_INIT_TIMEOUT_S"))
    if timeout_s:
        # feature-detect instead of try/except TypeError: a TypeError from
        # INSIDE initialize must not silently drop the user's timeout
        import inspect

        try:
            params = inspect.signature(jax.distributed.initialize).parameters
        except (TypeError, ValueError):
            params = {}
        if "initialization_timeout" in params:
            kwargs["initialization_timeout"] = timeout_s
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as e:
        from ..log import get_logger

        get_logger("mxnet_tpu.dist").error(
            "process group rendezvous failed: coordinator=%s rank=%d/%d "
            "(%r). Check that the coordinator host:port is reachable, that "
            "ALL %d workers launched, and that every rank in [0, %d) is "
            "claimed exactly once (MXNET_PROCESS_ID / DMLC_WORKER_ID).",
            coordinator, process_id, num_processes, e,
            num_processes, num_processes)
        raise
    _initialized = True
    # arm the elastic heartbeat lease (no-op unless MXNET_ELASTIC=1 with a
    # shared lease dir and real peers): from here on a dead worker raises
    # WorkerLostError inside collectives instead of parking the fleet
    from . import elastic

    elastic.ensure_started()


def _env_coordinator():
    if os.environ.get("MXNET_COORDINATOR"):
        return os.environ["MXNET_COORDINATOR"]
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    if not uri:
        return None
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
    return f"{uri}:{port}"


def process_rank():
    return jax.process_index()


def process_count():
    return jax.process_count()


def device_count():
    return len(jax.devices())


# -- cached collective programs ----------------------------------------------

@functools.lru_cache(maxsize=None)
def _collective_mesh():
    """Flat 1-D mesh over every device in the job."""
    return Mesh(np.array(jax.devices()), ("procdev",))


def _sum_over_devices_fn():
    # jit caches per input shape/dtype; one wrapper suffices for all keys
    def build():
        mesh = _collective_mesh()
        return jax.jit(lambda x: x.sum(axis=0),
                       out_shardings=NamedSharding(mesh, P()))

    return _dist_cache.get_or_build(("sum",), build)


def _gather_fn():
    """Replicate a device-sharded stack everywhere (AllGather)."""
    def build():
        mesh = _collective_mesh()
        return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))

    return _dist_cache.get_or_build(("gather",), build)


def _dequant_sum_fn(segments, threshold, dtype_str):
    return _dist_cache.get_or_build(
        ("dequant", segments, threshold, dtype_str),
        lambda: _build_dequant_sum(segments, threshold, dtype_str))


def _build_dequant_sum(segments, threshold, dtype_str):
    """One fused program: dequantize every worker's packed 2-bit words for a
    whole key bucket and sum over workers. ``segments`` is a static tuple of
    (word_start, word_count, shape) per key."""
    from ..gradient_compression import dequantize_2bit

    mesh = _collective_mesh()
    dtype = jnp.dtype(dtype_str)

    def body(packed_stack):  # (n_dev, total_words) uint32
        outs = []
        for (ws, wc, shape) in segments:
            seg = packed_stack[:, ws:ws + wc]
            de = jax.vmap(lambda p: dequantize_2bit(p, shape, threshold, dtype))(seg)
            outs.append(de.sum(axis=0))
        return tuple(outs)

    return jax.jit(body, out_shardings=NamedSharding(mesh, P()))


def _make_global_stack(buf, fill=0):
    """Build the (n_dev, *buf.shape) global array: this process's ``buf`` on
    its first local device, a neutral ``fill`` on its other local devices
    (so a sum over axis 0 is the sum over processes, and gathers can filter
    the neutral rows). No host round-trip."""
    mesh = _collective_mesh()
    n_dev = len(jax.devices())
    sharding = NamedSharding(mesh, P("procdev"))
    local = jax.local_devices()
    shards = []
    for i, d in enumerate(local):
        if i == 0:
            shards.append(jax.device_put(jnp.expand_dims(buf, 0), d))
        else:
            shards.append(jax.device_put(
                jnp.full((1,) + buf.shape, fill, buf.dtype), d))
    return jax.make_array_from_single_device_arrays(
        (n_dev,) + tuple(buf.shape), sharding, shards)


def _collective_telemetry(name, buf, t0):
    """Record one collective: bytes on the wire (this process's
    contribution) and host-side dispatch latency. jax dispatch is async, so
    the latency histogram is the host cost of issuing the collective — the
    device-side time shows up in the XLA trace (`profiler.start`)."""
    telemetry.counter(f"dist.{name}_calls").inc()
    telemetry.counter(f"dist.{name}_bytes").inc(
        int(buf.size) * buf.dtype.itemsize)
    telemetry.histogram(f"dist.{name}_us").record(
        (_time.perf_counter() - t0) * 1e6)


def _guarded(fn, desc):
    """Dispatch one cross-process collective under the elastic lease guard
    when the runtime is armed (the guard thread also blocks on the result,
    so a wedge surfaces as WorkerLostError instead of a later silent
    hang); plain dispatch otherwise."""
    from . import elastic

    if elastic.active():
        return elastic.guard(lambda: jax.block_until_ready(fn()), desc=desc)
    return fn()


def _allreduce_sum(buf):
    """Sum ``buf`` over all worker processes; replicated result (one
    AllReduce on the wire)."""
    if jax.process_count() == 1 and jax.local_device_count() == len(jax.devices()):
        return buf
    tele = telemetry._enabled  # cached across the call (mid-call enable)
    t0 = _time.perf_counter() if tele else 0.0
    stack = _make_global_stack(buf)
    out = _guarded(lambda: _sum_over_devices_fn()(stack), "allreduce")
    if tele:
        _collective_telemetry("allreduce", buf, t0)
    return out.addressable_data(0)


def _allgather(buf, fill=0):
    """All-gather ``buf`` from every device → replicated (n_dev, *shape).
    Rows from non-primary local devices hold the neutral ``fill``."""
    tele = telemetry._enabled  # cached across the call (mid-call enable)
    t0 = _time.perf_counter() if tele else 0.0
    stack = _make_global_stack(buf, fill=fill)
    out = _guarded(lambda: _gather_fn()(stack), "allgather")
    if tele:
        _collective_telemetry("allgather", buf, t0)
    return out.addressable_data(0)


def _bucket_cap_elems(itemsize):
    """Elements per fused-collective bucket. `MXNET_KVSTORE_DIST_BUCKET_SIZE`
    (elements — the original knob) wins when set; otherwise the shared
    grad-sync sizing knob `MXNET_KVSTORE_BUCKET_MB` (bytes) applies, so one
    variable sizes both the in-store bucketing and `GradSync` buckets."""
    env = os.environ.get("MXNET_KVSTORE_DIST_BUCKET_SIZE")
    if env:
        return int(env)
    from .grad_sync import bucket_cap_bytes

    return max(1, bucket_cap_bytes() // max(int(itemsize), 1))


def _wire_dtype(dtype, fp32_wire):
    """16-bit keys ship over a bf16 wire by default (fp32 exponent range,
    half the bytes); `MXNET_KVSTORE_FP32_WIRE=1` restores the exact wire."""
    if jnp.dtype(dtype) in (jnp.float16, jnp.bfloat16):
        return jnp.float32 if fp32_wire else jnp.bfloat16
    return jnp.dtype(dtype)


class KVStoreDistTPUSync(KVStoreBase):
    """`kv.create('dist_tpu_sync')` / `'dist_sync'` / `'dist'`.

    Keeps the KVStore front API (init/push/pull/pushpull, `kvstore.py`;
    subclasses KVStoreBase so `isinstance` dispatch in
    `model._create_kvstore` accepts store instances) so Trainer/Module code
    is unchanged, but push+pull together are ONE AllReduce over every
    device in the mesh — per-bucket programs are compile-cached by shape.
    Keys live replicated on the mesh.

    Semantics vs reference (`kvstore_dist_server.h` sync mode): the server
    aggregated exactly num_workers pushes then answered pulls; here the
    collective IS the aggregation+broadcast, so a push must be made by all
    workers collectively (SPMD) — same contract sync training already obeys.
    """

    def __init__(self, mesh=None):
        init_process_group()
        super().__init__()         # _updater/_updater_func/_gc
        self.mesh = mesh or default_mesh()
        self._store = {}           # key -> replicated jax Array
        self._pending = {}         # key -> aggregated dense grad
        self._pending_rsp = {}     # key -> list of (idx int32 (m,), data (m, ...))
        self._optimizer = None

    # -- identity -----------------------------------------------------------

    @property
    def type(self):
        return "dist_tpu_sync"

    @property
    def rank(self):
        return process_rank()

    @property
    def num_workers(self):
        return process_count()

    # -- data plane ----------------------------------------------------------

    def _key_list(self, key, value):
        from ..base import MXNetError

        if isinstance(key, (list, tuple)):
            # survive `python -O`: a stripped assert would zip-truncate and
            # silently drop the tail keys of a grouped call
            if len(key) != len(value):
                raise MXNetError(
                    f"grouped call: {len(key)} keys but {len(value)} values")
            return list(key), list(value)
        return [key], [value]

    def init(self, key, value):
        """Set initial values (never compressed — reference inits bypass
        gradient compression, `tests/nightly/dist_sync_kvstore.py:274-284`)."""
        from ..base import MXNetError
        from ..ndarray import NDArray

        keys, vals = self._key_list(key, value)
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            self._store[k] = jnp.asarray(arr)

    def push(self, key, value, priority=0, ignore_sparse=True):
        """Aggregate grads over all workers into the pending buffer."""
        from ..base import MXNetError
        from ..kvstore import _nd_nbytes
        from ..ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray

        tele = telemetry._enabled
        t0 = _time.perf_counter() if tele else 0.0
        keys, vals = self._key_list(key, value)
        if tele:
            telemetry.counter("kvstore.push_bytes").inc(sum(
                sum(_nd_nbytes(x) for x in v) if isinstance(v, (list, tuple))
                else _nd_nbytes(v) for v in vals))
        prios = list(priority) if isinstance(priority, (list, tuple)) \
            else [priority] * len(keys)
        dense_keys, dense_arrs, dense_prios = [], [], []
        for k, v, p in zip(keys, vals, prios):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized (call init first)")
            if isinstance(v, RowSparseNDArray):
                self._push_row_sparse(k, v)
                continue
            if isinstance(v, (list, tuple)):  # per-device list → local sum first
                arr = _local_sum([x._data if isinstance(x, NDArray) else x for x in v])
            else:
                arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            dense_keys.append(k)
            dense_arrs.append(arr)
            dense_prios.append(p)
        if dense_keys:
            if self._gc.active:
                self._push_dense_compressed(dense_keys, dense_arrs)
            else:
                self._push_dense(dense_keys, dense_arrs, dense_prios)
        if tele:
            telemetry.histogram("kvstore.push_us").record(
                (_time.perf_counter() - t0) * 1e6)

    def _push_dense(self, keys, arrs, priorities=None):
        """Bucketed allreduce: flatten+concat per dtype, one collective per
        bucket, split back per key. Grouped (multi-key) pushes fill buckets
        in priority order — least negative first, so the parameters the
        next forward pass consumes first are reduced first (the engine
        semantics the per-key `priority=-i` argument always promised).

        Wire dtype for 16-bit keys (round-5 verdict #9): fp16 gradients
        ship over a **bf16 wire** — the same bytes as the reference's
        native-dtype allreduce (`src/kvstore/comm.h:451`) but with fp32's
        exponent range, so large-key sums cannot overflow the way a raw
        fp16 wire can; bf16 keys stay bf16. `MXNET_KVSTORE_FP32_WIRE=1`
        restores the (exact, 2x bytes) fp32 wire for either."""
        order = range(len(keys))
        if priorities is not None and len(set(priorities)) > 1:
            order = sorted(order, key=lambda i: -priorities[i])
        buckets = []  # list of (keys, arrs)
        groups = {}
        for i in order:
            k, a = keys[i], arrs[i]
            groups.setdefault(str(a.dtype), []).append((k, a))
        # reference key-batching knob: cap KEYS per fused collective
        # too (kvstore_nccl.h update aggregation); 0 = elements only.
        # Read once per push — not per dtype group on the sync hot path
        key_cap = int(getenv("MXNET_UPDATE_AGGREGATION_SIZE", 0))
        for _, ka in groups.items():
            cap = _bucket_cap_elems(ka[0][1].dtype.itemsize)
            cur_k, cur_a, cur_n = [], [], 0
            for k, a in ka:
                if cur_k and (cur_n + a.size > cap
                              or (key_cap and len(cur_k) >= key_cap)):
                    buckets.append((cur_k, cur_a))
                    cur_k, cur_a, cur_n = [], [], 0
                cur_k.append(k)
                cur_a.append(a)
                cur_n += a.size
            if cur_k:
                buckets.append((cur_k, cur_a))
        fp32_wire = os.environ.get("MXNET_KVSTORE_FP32_WIRE", "0") == "1"
        tele = telemetry._enabled
        for bkeys, barrs in buckets:
            wire_dtype = _wire_dtype(barrs[0].dtype, fp32_wire)
            if tele:
                # exact wire-dispatch accounting: ONE collective per bucket
                # (the O(#buckets) contract test_grad_sync.py pins)
                telemetry.counter("dist.push_collectives").inc()
            if len(barrs) == 1:
                reduced = _allreduce_sum(barrs[0].astype(wire_dtype))
                parts = [reduced]
            else:
                flat = jnp.concatenate([a.reshape(-1).astype(wire_dtype) for a in barrs])
                red = _allreduce_sum(flat)
                parts, off = [], 0
                for a in barrs:
                    parts.append(red[off:off + a.size].reshape(a.shape))
                    off += a.size
            for k, a, p in zip(bkeys, barrs, parts):
                p = p.astype(a.dtype)
                pend = self._pending.get(k)
                self._pending[k] = p if pend is None else pend + p

    def _push_dense_compressed(self, keys, arrs):
        """2-bit compressed push: quantize locally (error feedback), ship
        packed words over one all-gather, dequantize+sum in one program."""
        segments, packs = [], []
        off = 0
        for k, a in zip(keys, arrs):
            packed = self._gc.quantize(k, a.astype(jnp.float32))
            segments.append((off, packed.shape[0], tuple(a.shape)))
            packs.append(packed)
            off += packed.shape[0]
        bucket = packs[0] if len(packs) == 1 else jnp.concatenate(packs)
        if telemetry._enabled:
            telemetry.counter("dist.push_collectives").inc()
        stack = _make_global_stack(bucket)  # fill=0 words dequantize to 0
        fn = _dequant_sum_fn(tuple(segments), float(self._gc.threshold), "float32")
        outs = _guarded(lambda: fn(stack), "compressed_push")
        for k, a, o in zip(keys, arrs, outs):
            p = o.addressable_data(0).astype(a.dtype)
            pend = self._pending.get(k)
            self._pending[k] = p if pend is None else pend + p

    def _push_row_sparse(self, k, v):
        """Ship only the occupied rows: all-gather (indices, rows) padded to
        the max per-worker row count (reference EncodeRowSparseKey,
        `kvstore_dist.h:676`); aggregation stays sparse until update time."""
        idx = v.indices._data.astype(jnp.int32)
        data = v.data._data
        n_proc = self.num_workers
        if n_proc == 1:
            if idx.size:
                self._pending_rsp.setdefault(k, []).append((idx, data))
            else:
                self._pending_rsp.setdefault(k, [])
            return
        counts = _allgather(jnp.asarray([idx.shape[0]], jnp.int32))
        cap = int(np.asarray(counts).max())
        self._pending_rsp.setdefault(k, [])
        if cap == 0:
            return
        row_shape = tuple(self._store[k].shape[1:])
        pad_idx = jnp.full((cap,), -1, jnp.int32).at[:idx.shape[0]].set(idx)
        pad_data = jnp.zeros((cap,) + row_shape, data.dtype)
        if idx.shape[0]:
            pad_data = pad_data.at[:idx.shape[0]].set(data)
        all_idx = np.asarray(_allgather(pad_idx, fill=-1))  # (n_dev, cap)
        all_data = _allgather(pad_data)                     # (n_dev, cap, ...)
        pieces_i, pieces_d = [], []
        for r in range(all_idx.shape[0]):
            valid = all_idx[r] >= 0
            if valid.any():
                pieces_i.append(jnp.asarray(all_idx[r][valid]))
                pieces_d.append(all_data[r][np.nonzero(valid)[0]])
        if pieces_i:
            self._pending_rsp[k].append(
                (jnp.concatenate(pieces_i), jnp.concatenate(pieces_d)))

    def _merged_rsp(self, k):
        """Merge pending sparse pieces: unique rows + segment sum."""
        pieces = self._pending_rsp.pop(k)
        if not pieces:
            return None
        idx = jnp.concatenate([p[0] for p in pieces])
        data = jnp.concatenate([p[1] for p in pieces])
        uniq, inv = jnp.unique(idx, return_inverse=True)
        summed = jax.ops.segment_sum(data, inv.reshape(-1), num_segments=uniq.shape[0])
        return uniq, summed

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from ..base import MXNetError
        from ..kvstore import _nd_nbytes
        from ..ndarray import NDArray

        tele = telemetry._enabled
        t0 = _time.perf_counter() if tele else 0.0
        keys, outs = self._key_list(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized (call init first)")
            self._apply_pending(k)
            val = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            if tele:
                telemetry.counter("kvstore.pull_bytes").inc(
                    sum(_nd_nbytes(t) for t in targets))
            for t in targets:
                t._data = jnp.asarray(val, t.dtype)
        if tele:
            telemetry.histogram("kvstore.pull_us").record(
                (_time.perf_counter() - t0) * 1e6)

    def _apply_pending(self, k):
        from ..ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray

        if k in self._pending_rsp:
            merged = self._merged_rsp(k)
            stored = self._store[k]
            if merged is None:
                # every worker pushed an empty row_sparse grad: with an
                # updater that's a no-op update; without one, stored becomes
                # the (all-zero) aggregate (kvstore_dist_server.h ApplyUpdates)
                if self._updater is None:
                    self._store[k] = jnp.zeros_like(stored)
                return
            uniq, summed = merged
            if self._updater is not None:
                grad = RowSparseNDArray(NDArray(summed.astype(stored.dtype)),
                                        NDArray(uniq.astype(jnp.int32)),
                                        tuple(stored.shape))
                w = NDArray(stored)
                self._updater(_key_index(k), grad, w)
                self._store[k] = w._data
            else:
                # sync mode without updater: stored = merged (CopyFromTo of
                # the row_sparse aggregate, kvstore_dist_server.h ApplyUpdates)
                dense = jnp.zeros_like(stored).at[uniq].set(summed.astype(stored.dtype))
                self._store[k] = dense
            return
        pend = self._pending.pop(k, None)
        if pend is None:
            return
        if self._updater is not None:
            stored = NDArray(self._store[k])
            self._updater(_key_index(k), NDArray(pend), stored)
            self._store[k] = stored._data
        else:
            self._store[k] = jnp.asarray(pend, self._store[k].dtype)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def allreduce_flat(self, value, priority=0):
        """One bucket = one AllReduce on the wire (`GradSync`'s collective):
        local-sum the per-device replicas, then one cross-worker collective
        over the flat buffer — no store, no updater, no per-key dispatch."""
        from ..kvstore import _nd_nbytes
        from ..ndarray import NDArray

        vals = value if isinstance(value, (list, tuple)) else [value]
        arrs = [v._data if isinstance(v, NDArray) else jnp.asarray(v)
                for v in vals]
        dtype = arrs[0].dtype
        fp32_wire = os.environ.get("MXNET_KVSTORE_FP32_WIRE", "0") == "1"
        wire = _wire_dtype(dtype, fp32_wire)
        # cast BEFORE the local-device sum: a flat fp16 bucket sums in the
        # wire dtype end-to-end, so neither the replica sum nor the
        # cross-worker sum can overflow fp16's exponent
        arrs = [a.astype(wire) for a in arrs]
        buf = arrs[0] if len(arrs) == 1 else _local_sum(arrs)
        if telemetry._enabled:
            telemetry.counter("dist.push_collectives").inc()
            telemetry.counter("dist.bucket_bytes").inc(
                int(buf.size) * buf.dtype.itemsize)
        reduced = _allreduce_sum(buf)
        return NDArray(reduced.astype(dtype))

    def reduce_scatter_flat(self, value, num_shards, shard_index,
                            priority=0):
        """Reduce-scatter across workers — the ZeRO-1 eager wire primitive
        next to `allreduce_flat`: each worker gets back only its
        1/num_shards slice of the cross-worker sum. This eager lane always
        ships the FULL allreduce bytes and slices host-side after the
        collective (gloo has no reduce-scatter primitive); the true
        (N-1)/N·B ReduceScatter exists only on the traced path, where XLA
        lowers zero1.py's psum + sharding constraint onto ICI."""
        from ..base import MXNetError
        from ..ndarray import NDArray

        vals = value if isinstance(value, (list, tuple)) else [value]
        n = int(vals[0].shape[0])
        if n % int(num_shards):
            raise MXNetError(
                f"reduce_scatter_flat: bucket length {n} not divisible "
                f"into {num_shards} shards (pad with pad_to_shards first)")
        step = n // int(num_shards)
        lo = step * int(shard_index)
        merged = self.allreduce_flat(value, priority)
        return NDArray(merged._data[lo:lo + step])

    @property
    def fused_step_compatible(self):
        """The fused train step may trace this store's gradient sync when
        the collective is expressible inside the module's (single-device)
        jitted program: a single-process group, where the cross-replica sum
        degenerates to the identity. Multi-host groups and compressed
        pushes keep the eager decomposition (per-push quantization needs
        host-side residual state)."""
        return process_count() == 1 and not self._gc.active

    def fused_grad_sync_fn(self, entries):
        """Traceable bucketed gradient sync for `Executor.fused_step`:
        flatten+concat each bucket and apply the cross-replica sum INSIDE
        the jitted step (the psum the eager push dispatches per bucket) —
        instead of falling back to eager whenever a kvstore is attached.
        With one process the sum over the replica group is the identity,
        but the bucket pack/reduce/unpack structure stays in the trace, so
        the wire dtype and key→bucket layout match the eager path exactly.

        ZeRO-1 composition (`MXNET_ZERO1=1`): the sharded update
        (`parallel/zero1.py`) runs downstream of this sync in the same
        trace and immediately re-constrains each bucket to the dp-sharded
        layout — XLA fuses the cross-replica sum + sharded constraint into
        ONE ReduceScatter (the reduce-scatter variant of this allreduce,
        arXiv:2004.13336), so no second wire pass is paid."""
        if not self.fused_step_compatible:
            return None
        from .grad_sync import bucket_assign, bucket_cap_bytes

        buckets = bucket_assign(list(entries), bucket_cap_bytes())
        shapes = [tuple(e[0]) for e in entries]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        fp32_wire = os.environ.get("MXNET_KVSTORE_FP32_WIRE", "0") == "1"

        def sync(grads):
            out = list(grads)
            for b in buckets:
                wire = _wire_dtype(b.dtype, fp32_wire)
                parts = [out[k].reshape(-1).astype(wire) for k in b.keys]
                flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
                # single-process group: sum over replicas == identity; the
                # multi-host lowering replaces this with lax.psum over the
                # dp axis of an SPMD trace
                off = 0
                for k in b.keys:
                    out[k] = flat[off:off + sizes[k]].reshape(
                        shapes[k]).astype(grads[k].dtype)
                    off += sizes[k]
            return tuple(out)

        return sync

    def pull_sparse_grad(self, key):
        """Hand back the merged pending row_sparse aggregate as
        (unique_rows, summed_data) WITHOUT applying it to the stored value
        or densifying — gluon Trainer's allreduce-then-update-locally flow
        (the reference pulls row_sparse grads the same lazy way)."""
        merged = self._merged_rsp(key) if key in self._pending_rsp else None
        if merged is None:
            val = self._store[key]
            return (jnp.zeros((0,), jnp.int32),
                    jnp.zeros((0,) + tuple(val.shape[1:]), val.dtype))
        return merged

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference `PullRowSparseImpl`,
        `kvstore_dist.h:271`): result has the full logical shape with the
        deduplicated requested rows filled, everything else zero. A
        RowSparseNDArray ``out`` receives just (indices, rows) — O(rows),
        no dense table is built."""
        from ..ndarray import NDArray

        keys, outs = self._key_list(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids] * len(keys)
        for k, o, r in zip(keys, outs, rids):
            self._apply_pending(k)
            val = self._store[k]
            ridx = r._data if isinstance(r, NDArray) else jnp.asarray(r)
            ridx = jnp.unique(ridx.reshape(-1).astype(jnp.int32)) if ridx.size \
                else jnp.zeros((0,), jnp.int32)
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                _fill_rows(t, val, ridx)

    # -- control plane -------------------------------------------------------

    def set_optimizer(self, optimizer):
        from .. import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        self._gc.set_params(compression_params)

    def barrier(self):
        """Fleet sync point, with straggler diagnostics. Under the elastic
        runtime (`MXNET_ELASTIC=1`) the straggler warning is promoted to a
        STRUCTURED timeout: the barrier runs under the heartbeat-lease
        guard, so a dead or wedged worker raises `WorkerLostError` within
        `MXNET_ELASTIC_GRACE_S` and the survivor can shrink+resume. On the
        non-elastic path a barrier slower than `MXNET_BARRIER_WARN_S`
        keeps the original behavior — log which rank noticed and how long
        it stalled, and keep waiting — because without a rendezvous to
        shrink through, aborting is strictly worse than diagnosing."""
        from ..base import getenv
        from ..log import get_logger
        from . import elastic

        warn_s = float(getenv("MXNET_BARRIER_WARN_S"))
        t0 = _time.monotonic()
        if elastic.active():
            elastic.guard(lambda: coll.barrier(self.mesh), desc="barrier")
        else:
            coll.barrier(self.mesh)
        elapsed = _time.monotonic() - t0
        if telemetry._enabled:
            # straggler wait: time THIS rank sat parked at the sync point —
            # p99 across steps is the fleet's straggler profile
            telemetry.histogram("dist.barrier_wait_us").record(elapsed * 1e6)
        if elapsed > warn_s:
            get_logger("mxnet_tpu.dist").warning(
                "barrier on rank %d/%d took %.1fs (threshold %.0fs) — a "
                "straggler or dead worker is holding the fleet",
                self.rank, self.num_workers, elapsed, warn_s)

    # save/load_optimizer_states inherit KVStoreBase's MXNetError-guarded
    # implementations (every rank runs the same updater on the replicated
    # aggregate, so local state IS the global state)


def _fill_rows(target, val, ridx):
    """Write the selected rows of ``val`` into ``target``: sparse targets
    get only (indices, rows); dense targets get the zero-padded full shape."""
    from ..ndarray import NDArray
    from ..ndarray.sparse import RowSparseNDArray

    if isinstance(target, RowSparseNDArray):
        rows = jnp.take(val, ridx, axis=0) if ridx.size else \
            jnp.zeros((0,) + tuple(val.shape[1:]), val.dtype)
        target._aux = {"data": NDArray(rows.astype(target.dtype)),
                       "indices": NDArray(ridx)}
        target._dense_cache = None
        target._aux_stale = False
        return
    result = jnp.zeros_like(val)
    if ridx.size:
        result = result.at[ridx].set(jnp.take(val, ridx, axis=0))
    target._data = jnp.asarray(result, target.dtype)


def _key_index(k):
    """String keys map through the SAME deterministic index as the local
    kvstore (`kvstore._str_key_int`) so optimizer states saved under one
    store type resume correctly under the other."""
    if isinstance(k, int):
        return k
    from ..kvstore import _str_key_int

    return _str_key_int(k)


def _local_sum(arrs):
    out = arrs[0]
    for a in arrs[1:]:
        out = out + jnp.asarray(a, out.dtype)
    return out
