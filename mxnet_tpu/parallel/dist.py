"""Multi-host process group + the `dist_tpu_sync` KVStore.

Replaces ps-lite entirely (SURVEY.md §5): the reference runs a scheduler +
N server processes + M workers over ZMQ (`kvstore_dist.h:44`,
`kvstore_dist_server.h:155`), shards big keys across servers
(`EncodeDefaultKey:533`), and applies the optimizer server-side
(`ApplyUpdates:346`). On TPU there are no servers: every host joins one
SPMD process group (`jax.distributed`), arrays are global, and a push is an
AllReduce over ICI (DCN across slices) inside a tiny jitted program.
update_on_kvstore maps to False — allreduce + local (replicated) update —
the Horovod-style flow the reference itself uses at `gluon/trainer.py:327`.
"""
from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import default_mesh, create_mesh
from . import collectives as coll

_initialized = False


def init_process_group(coordinator=None, num_processes=None, process_id=None):
    """Initialise jax.distributed from args or env (no-op single process).

    Env rendezvous keeps the reference's names working where they map:
    `DMLC_PS_ROOT_URI`/`DMLC_PS_ROOT_PORT` → coordinator address,
    `DMLC_NUM_WORKER` → process count, `DMLC_WORKER_ID` → process id
    (ps-lite's scheduler rendezvous, minus the scheduler).
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or _env_coordinator()
    if coordinator is None:
        _initialized = True  # single-process
        return
    num_processes = num_processes or int(os.environ.get("DMLC_NUM_WORKER", "1"))
    process_id = process_id if process_id is not None else int(os.environ.get("DMLC_WORKER_ID", "0"))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def _env_coordinator():
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    if not uri:
        return None
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
    return f"{uri}:{port}"


def process_rank():
    return jax.process_index()


def process_count():
    return jax.process_count()


def device_count():
    return len(jax.devices())


class KVStoreDistTPUSync:
    """`kv.create('dist_tpu_sync')` / `'dist_sync'` / `'dist'`.

    Keeps the KVStore front API (init/push/pull/pushpull, `kvstore.py`) so
    Trainer/Module code is unchanged, but push+pull together are ONE
    AllReduce over every device in the mesh — per-key programs are compile-
    cached by shape. Keys live replicated on the mesh.

    Semantics vs reference (`kvstore_dist_server.h` sync mode): the server
    aggregated exactly num_workers pushes then answered pulls; here the
    collective IS the aggregation+broadcast, so a push must be made by all
    workers collectively (SPMD) — same contract sync training already obeys.
    """

    def __init__(self, mesh=None):
        init_process_group()
        self.mesh = mesh or default_mesh()
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    # -- identity -----------------------------------------------------------

    @property
    def type(self):
        return "dist_tpu_sync"

    @property
    def rank(self):
        return process_rank()

    @property
    def num_workers(self):
        return process_count()

    # -- data plane ----------------------------------------------------------

    def _key_list(self, key, value):
        if isinstance(key, (list, tuple)):
            assert len(key) == len(value)
            return list(key), list(value)
        return [key], [value]

    def init(self, key, value):
        from ..ndarray import NDArray

        keys, vals = self._key_list(key, value)
        repl = NamedSharding(self.mesh, P())
        for k, v in zip(keys, vals):
            arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            self._store[k] = jax.device_put(arr, repl)

    def push(self, key, value, priority=0, ignore_sparse=True):
        """Accumulate grads: AllReduce(value) into the pending buffer."""
        from ..ndarray import NDArray

        keys, vals = self._key_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):  # per-device list → local sum first
                arr = _local_sum([x._data if isinstance(x, NDArray) else x for x in v])
            else:
                arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            reduced = self._allreduce(arr)
            pend = self._store.get(("pending", k))
            self._store[("pending", k)] = reduced if pend is None else pend + reduced

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from ..ndarray import NDArray

        keys, outs = self._key_list(key, out)
        for k, o in zip(keys, outs):
            pend = self._store.pop(("pending", k), None)
            if pend is not None:
                if self._updater is not None:
                    # update_on_kvstore=True path: run optimizer on the
                    # aggregated grad, replicated everywhere (the TPU
                    # version of server-side ApplyUpdates)
                    stored = NDArray(self._store[k])
                    kk = k if isinstance(k, int) else _stable_key_index(k)
                    self._updater(kk, NDArray(pend), stored)
                    self._store[k] = stored._data
                else:
                    self._store[k] = pend
            val = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._data = jnp.asarray(val, t.dtype)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Sparse pull: gather the requested rows from the replicated value
        (reference `PullRowSparseImpl`, `kvstore_dist.h:271`)."""
        from ..ndarray import NDArray

        keys, outs = self._key_list(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids] * len(keys)
        for k, o, r in zip(keys, outs, rids):
            val = self._store[k]
            idx = r._data.astype(jnp.int32) if isinstance(r, NDArray) else jnp.asarray(r, jnp.int32)
            rows = jnp.take(val, idx, axis=0)
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._data = rows

    # -- control plane -------------------------------------------------------

    def set_optimizer(self, optimizer):
        from .. import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        self._compression = dict(compression_params)

    def barrier(self):
        coll.barrier(self.mesh)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- internals -----------------------------------------------------------

    def _allreduce(self, arr):
        """Sum this key's contribution over all WORKER PROCESSES, result
        replicated (the server-side aggregation of `kvstore_dist_server.h`
        sync mode, minus the server).

        Every device on this process holds an identical copy of the local
        grad, so mean-over-all-devices × process_count = sum over distinct
        process contributions — one ICI/DCN AllReduce, no ZMQ.
        """
        arr = jnp.asarray(arr)
        n_proc = self.num_workers
        if n_proc == 1:
            return arr
        # conversion and reduction must agree on one (flattened) mesh: a
        # multi-axis self.mesh would shard the stacked dim on axis 0 only
        # while the reduce runs over a different mesh
        mesh, axis = coll._flat_collective_mesh(self.mesh)
        from jax.experimental import multihost_utils
        local = np.stack([np.asarray(arr)] * jax.local_device_count())
        global_arr = multihost_utils.host_local_array_to_global_array(
            local, mesh, P(axis))
        reduced = coll.eager_all_reduce(global_arr, axis=axis, op="mean", mesh=mesh)
        # result is replicated per device along the stacked axis; local
        # shard 0 is addressable on every process
        local_out = [s.data for s in reduced.addressable_shards][0]
        return jnp.asarray(local_out[0] if local_out.ndim == arr.ndim + 1 else local_out) * n_proc


def _stable_key_index(key):
    """Deterministic int index for a string key — identical across worker
    processes and restarts (Python's str hash is salted per process, which
    would break idx2name-keyed lr/wd multipliers and optimizer-state
    save/load)."""
    import zlib

    return zlib.crc32(str(key).encode("utf-8")) & 0x3FFFFFFF


def _local_sum(arrs):
    out = arrs[0]
    for a in arrs[1:]:
        out = out + jnp.asarray(a, out.dtype)
    return out
