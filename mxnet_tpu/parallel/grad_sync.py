"""Cross-key bucketed, overlapped gradient synchronization.

The dist layer replaced ps-lite with SPMD collectives (`dist.py`) and
buckets keys *within one push call* — but until this module every trainer
(`module/module.py`, `model.py`, `gluon/trainer.py`) pushed ONE parameter
per call, so bucketing never engaged and each sync step paid
O(#parameters) collective dispatches. BANDWIDTH_r05.json quantifies the
cost: on resnet50_v1 the 151 small (<256KB) keys move ~1 MB/s at 4 workers
while the large tier moves ~141 MB/s on the same wire (~305 MB/s at 2
workers) — per-key dispatch overhead, not bandwidth, dominates.

This module is the gradient-sync scheduler that fixes it:

* **Bucketing** — parameters are assigned to fixed-size flat buckets
  (`MXNET_KVSTORE_BUCKET_MB`, grouped by dtype); each bucket is ONE
  flattened+concatenated buffer and ONE collective
  (`KVStoreBase.allreduce_flat`), so a sync step costs O(#buckets)
  collectives instead of O(#parameters). The flat buffers are persistent:
  the packed/reduced arrays of the previous step are kept alive per bucket
  so XLA's buffer reuse (and the cached pack/unpack executables) hit the
  same allocations step after step.

* **Overlap** — bucket collectives are ISSUED asynchronously in gradient
  readiness order (reverse-topological: the most negative push priority —
  the deepest layers, whose gradients backward produces first — goes on
  the wire first) and DRAINED in priority order (least negative first: the
  parameters the next forward pass consumes first). jax dispatch is
  asynchronous, so between issue and drain the collectives proceed on
  device while the host runs optimizer bookkeeping or the next data fetch;
  only :meth:`GradSync.drain` blocks. Telemetry derives an **overlap
  ratio** — the fraction of the sync window in which communication ran
  hidden behind other work (`grad_sync.overlap_ratio`).

* **Correctness reference** — `MXNET_GRAD_BUCKETING=0` restores the eager
  per-key push/pull path in every caller; `tests/python/unittest/
  test_grad_sync.py` pins bucketed == per-key bit-exactly on fp32.

The reduce-scatter refinement (shard the update itself, PAPERS.md arxiv
2004.13336) is implemented on top of this layout by `parallel/zero1.py`
(`MXNET_ZERO1=1`): a bucket's flat buffer is the reduce-scatter operand,
the optimizer update runs on each replica's 1/N slice, and
`KVStore.reduce_scatter_flat` is the eager wire primitive next to
`allreduce_flat`.
"""
from __future__ import annotations

import functools
import time as _time
from collections import namedtuple

import jax
import jax.numpy as jnp

from .. import telemetry
from .. import tracing
from ..base import getenv, register_env

__all__ = ["GradSync", "Bucket", "bucket_assign", "bucketing_enabled",
           "bucket_cap_bytes"]

register_env("MXNET_GRAD_BUCKETING", True,
             "bucket gradient sync (one collective per flat bucket); "
             "0 = eager per-key push/pull, the correctness reference")
register_env("MXNET_KVSTORE_BUCKET_MB", 4.0,
             "target flat gradient-sync bucket size in MB (per dtype)")


def bucketing_enabled():
    return bool(getenv("MXNET_GRAD_BUCKETING"))


def sync_compatible(kvstore):
    """Whether the flat-bucket allreduce preserves this store's push
    semantics. Gradient compression quantizes per key (with a per-key
    error-feedback residual) INSIDE push and has no bucket equivalent —
    a compressed store must keep the per-key path or compression would be
    silently disabled."""
    gc = getattr(kvstore, "_gc", None)
    return gc is None or not gc.active


def bucket_cap_bytes(bucket_mb=None):
    """Bucket size cap in bytes. A cap of 0 means one key per bucket (the
    per-key baseline expressed in the bucketed code path)."""
    mb = float(getenv("MXNET_KVSTORE_BUCKET_MB")) if bucket_mb is None \
        else float(bucket_mb)
    return int(mb * (1 << 20))


# One sync unit: ``keys`` index into the configure()-time entry list.
# ``priority`` is the max (least negative) member priority — the drain
# rank; issue order is the reverse.
Bucket = namedtuple("Bucket", ["keys", "dtype", "nbytes", "priority"])


def bucket_assign(entries, cap_bytes):
    """Assign entries to flat buckets.

    ``entries``: list of ``(shape, dtype, priority)`` in parameter order
    (priority is the caller's push priority, conventionally ``-index``).
    Walks the list in REVERSE — the order backward produces gradients — so
    each bucket fills with gradients that become ready together; buckets
    are per-dtype (a flat buffer has one dtype) and close when adding the
    next key would exceed ``cap_bytes`` (a single oversized key still gets
    its own bucket). Returns buckets in issue (readiness) order.
    """
    open_buckets = {}  # dtype -> (keys, nbytes, best_priority)
    out = []

    def _close(dt):
        keys, nbytes, prio = open_buckets.pop(dt)
        out.append(Bucket(tuple(keys), dt, nbytes, prio))

    for pos in reversed(range(len(entries))):
        shape, dtype, priority = entries[pos]
        dt = jnp.dtype(dtype)
        nbytes = int(jnp.zeros((), dt).itemsize)
        for d in shape:
            nbytes *= int(d)
        cur = open_buckets.get(dt)
        if cur is not None and cur[1] + nbytes > cap_bytes:
            _close(dt)
            cur = None
        if cur is None:
            open_buckets[dt] = ([pos], nbytes, priority)
        else:
            cur[0].append(pos)
            open_buckets[dt] = (cur[0], cur[1] + nbytes,
                                max(cur[2], priority))
    for dt in list(open_buckets):
        _close(dt)
    return out


@functools.lru_cache(maxsize=1)
def _cache():
    """Named CompileCache for the pack/unpack executables — like every
    other compiled-callable cache in the framework (`compile_cache.py`):
    recompiles show up in compile.* telemetry and the cache is bounded
    (layout churn, e.g. a --bucket-mb sweep, evicts oldest instead of
    growing forever). Built lazily: constructing it at import time would
    order-couple module imports."""
    from ..compile_cache import CompileCache

    # track_memory=False: hundreds of tiny pack/unpack programs — the
    # /memory scrape's per-entry AOT analysis would re-pay a compile each
    return CompileCache("grad_sync", maxsize=256, track_memory=False)


def _pack_fn(shapes, dtype):
    """Jitted flatten+concat for one bucket layout (compiled once per
    layout; reused every step — the persistent-flat-buffer program)."""
    def build():
        if len(shapes) == 1:
            return jax.jit(lambda x: x.reshape(-1).astype(dtype))

        def pack(*xs):
            return jnp.concatenate([x.reshape(-1).astype(dtype) for x in xs])

        return jax.jit(pack)

    return _cache().get_or_build(("pack", shapes, str(dtype)), build)


def _unpack_fn(shapes, dtype):
    """Jitted split+reshape back to per-key shapes."""
    def build():
        sizes = []
        for s in shapes:
            n = 1
            for d in s:
                n *= int(d)
            sizes.append(n)

        def unpack(flat):
            outs, off = [], 0
            for s, n in zip(shapes, sizes):
                outs.append(flat[off:off + n].reshape(s).astype(dtype))
                off += n
            return tuple(outs)

        return jax.jit(unpack)

    return _cache().get_or_build(("unpack", shapes, str(dtype)), build)


class GradSync:
    """Bucketed, overlapped gradient synchronizer over one kvstore.

    Usage (one step)::

        sched.configure(entries)        # idempotent per layout
        sched.issue(grads)              # async: one collective per bucket
        ... other host work (overlap window) ...
        sched.drain(grads)              # block + scatter reduced values

    ``sync(grads)`` = issue+drain for callers with nothing to overlap.
    ``grads[i]`` is an NDArray or a list of per-device NDArrays; the
    reduced (sum over devices and workers) value is written back into
    every replica — the same contract as eager ``push(k, g); pull(k, g)``.
    """

    def __init__(self, kvstore, bucket_mb=None):
        self._kv = kvstore
        self._cap = bucket_cap_bytes(bucket_mb)
        self._sig = None
        self._buckets = ()
        self._entries = ()
        # persistent flat buffers: bucket idx -> last packed/reduced array
        self._flat = {}
        self._inflight = []  # (bucket, reduced NDArray, t_issue)
        self._t_issue0 = 0.0
        self._t_issue1 = 0.0
        # memory census: the persistent flat reduce buffers are this
        # scheduler's device residency (a LIVE view — buffers are replaced
        # every step, so a snapshot weakref would die immediately)
        from .. import memory

        memory.register_provider("gradients", self,
                                 lambda s: list(s._flat.values()))

    @property
    def buckets(self):
        return self._buckets

    def configure(self, entries):
        """(Re)build the bucket plan for ``entries`` =
        [(shape, dtype, priority), ...] in parameter order. Cheap no-op
        when the layout is unchanged."""
        sig = tuple((tuple(s), str(jnp.dtype(d)), int(p))
                    for s, d, p in entries)
        if sig == self._sig:
            return
        self._sig = sig
        self._entries = tuple(entries)
        self._buckets = tuple(bucket_assign(list(entries), self._cap))
        self._flat.clear()
        if telemetry._enabled:
            telemetry.gauge("grad_sync.buckets").set(len(self._buckets))
            telemetry.gauge("grad_sync.keys").set(len(entries))

    def configure_from(self, arrays, priorities=None):
        """Convenience: build entries from NDArrays (or per-device lists)."""
        entries = []
        for i, a in enumerate(arrays):
            rep = a[0] if isinstance(a, (list, tuple)) else a
            prio = priorities[i] if priorities is not None else -i
            entries.append((tuple(rep.shape), rep.dtype, prio))
        self.configure(entries)

    # -- one bucket ----------------------------------------------------------

    def _pack(self, bucket, grads):
        """Flatten+concat this bucket's grads per device replica; returns a
        list of flat jax arrays (one per replica)."""
        shapes = tuple(self._entries[k][0] for k in bucket.keys)
        dtype = bucket.dtype
        per_key = [grads[k] if isinstance(grads[k], (list, tuple))
                   else [grads[k]] for k in bucket.keys]
        n_rep = len(per_key[0])
        fn = _pack_fn(shapes, dtype)
        return [fn(*[kg[r]._data for kg in per_key]) for r in range(n_rep)]

    def _scatter(self, bucket, flat, grads, outs):
        """Split the reduced flat buffer back into every replica of every
        key (outs defaults to grads — pull-into-grad semantics). Each
        replica is committed back to ITS device (the eager pull's
        `as_in_context` contract): the unpacked parts live on the reduce
        device, and a later per-device op mixing a weight on device r with
        a grad parked on device 0 would be a cross-device error."""
        shapes = tuple(self._entries[bi][0] for bi in bucket.keys)
        parts = _unpack_fn(shapes, bucket.dtype)(flat)
        parts = parts if isinstance(parts, tuple) else (parts,)
        target = outs if outs is not None else grads
        for bi, part in zip(bucket.keys, parts):
            tgt = target[bi]
            tgt = tgt if isinstance(tgt, (list, tuple)) else [tgt]
            for t in tgt:
                dev = getattr(t.context, "jax_device", None)
                val = jnp.asarray(part, t.dtype)
                t._data = val if dev is None else jax.device_put(val, dev)

    # -- step API ------------------------------------------------------------

    def issue(self, grads):
        """Dispatch one async collective per bucket, in gradient-readiness
        (reverse-topological) order. Does not block: the returned work is
        drained by :meth:`drain`."""
        if self._inflight:  # a real error, not an assert (`python -O`):
            # double-issue would scatter every bucket twice at drain
            from ..base import MXNetError

            raise MXNetError("GradSync.issue() called twice without drain()")
        tele = telemetry._enabled
        trc = tracing._enabled
        self._t_issue0 = _time.perf_counter()
        with tracing.span("grad_sync.issue", cat="comm",
                          buckets=len(self._buckets)):
            for idx, bucket in enumerate(self._buckets):
                t_b = tracing.now_us() if trc else 0.0
                flats = self._pack(bucket, grads)
                t0 = _time.perf_counter()
                reduced = self._kv.allreduce_flat(flats,
                                                  priority=bucket.priority)
                self._flat[idx] = reduced  # persistent flat buffer
                self._inflight.append((bucket, reduced, t0))
                if trc:
                    tracing.emit_span("grad_sync.bucket_issue", t_b,
                                      tracing.now_us() - t_b, cat="comm",
                                      bucket=idx, nbytes=bucket.nbytes,
                                      keys=len(bucket.keys),
                                      priority=bucket.priority)
                if tele:
                    telemetry.counter("grad_sync.collectives").inc()
                    telemetry.counter("grad_sync.bytes").inc(bucket.nbytes)
                    telemetry.histogram("grad_sync.issue_us").record(
                        (_time.perf_counter() - t0) * 1e6)
        self._t_issue1 = _time.perf_counter()

    def drain(self, grads, outs=None):
        """Block on the in-flight collectives (priority order: least
        negative — the front of the network — first) and scatter the
        reduced values back. Records the overlap ratio: of the wall time
        between the end of issue() and the end of drain(), the fraction
        NOT spent blocked on communication — comm hidden behind compute."""
        tele = telemetry._enabled
        trc = tracing._enabled
        waited = 0.0
        try:
            with tracing.span("grad_sync.drain", cat="comm",
                              buckets=len(self._inflight)):
                for bucket, reduced, _t0 in sorted(
                        self._inflight, key=lambda x: -x[0].priority):
                    t_b = tracing.now_us() if trc else 0.0
                    t0 = _time.perf_counter()
                    jax.block_until_ready(reduced._data)
                    blocked = _time.perf_counter() - t0
                    waited += blocked
                    self._scatter(bucket, reduced._data, grads, outs)
                    if trc:
                        tracing.emit_span(
                            "grad_sync.bucket_drain", t_b,
                            tracing.now_us() - t_b, cat="comm",
                            nbytes=bucket.nbytes, keys=len(bucket.keys),
                            priority=bucket.priority,
                            blocked_us=int(blocked * 1e6))
        finally:
            # a failed collective (dead worker mid-allreduce) must not wedge
            # the scheduler: clear in-flight work so the caller's next
            # issue() sees the REAL error path, not the double-issue assert
            self._inflight = []
        if tele:
            t_end = _time.perf_counter()
            window = max(t_end - self._t_issue1, 1e-12)
            ratio = max(0.0, min(1.0, 1.0 - waited / window))
            telemetry.histogram("grad_sync.exposed_wait_us").record(
                waited * 1e6)
            telemetry.histogram("grad_sync.sync_us").record(
                (t_end - self._t_issue0) * 1e6)
            telemetry.gauge("grad_sync.overlap_ratio").set(ratio)

    def sync(self, grads, outs=None):
        """issue + drain in one call (no caller-side overlap window)."""
        self.issue(grads)
        self.drain(grads, outs=outs)
