"""Parallelism & distributed communication over TPU meshes.

This package is the TPU-native answer to the reference's entire distributed
stack (SURVEY.md §2.4): `src/kvstore/` (local/device/NCCL/ps-lite),
`comm.h`/`comm_tree.h` device reduce trees, and `tools/launch.py` cluster
bootstrap. Design: one `jax.sharding.Mesh` with named axes, sharding
annotations on a single jitted SPMD program, XLA collectives over ICI/DCN.

Axes convention (any subset may be present, size 1 axes are free):
  dp    data parallelism (batch dimension)
  fsdp  parameter sharding on the data axis (ZeRO-style)
  tp    tensor (model) parallelism
  sp    sequence/context parallelism (ring attention)
  pp    pipeline stages
  ep    expert parallelism (MoE)
"""
from .mesh import (
    MeshSpec, create_mesh, default_mesh, current_mesh, use_mesh, local_mesh,
    dp_mesh, pp_mesh, mesh_from_env, axis_size, has_axis,
    AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_PP, AXIS_EP,
)
from .collectives import (
    all_reduce, all_gather, reduce_scatter, ppermute, barrier, psum_scatter,
    sharding_constraint,
)
from .dist import (
    init_process_group, process_rank, process_count, device_count,
    KVStoreDistTPUSync,
)
from .grad_sync import GradSync, bucket_assign, bucketing_enabled
from .zero1 import Zero1Context, zero1_enabled
from .data_parallel import ShardedTrainer, shard_batch, replicate
from .partition import (
    PartitionRules, infer_param_sharding, replicated, flat_shard,
    pad_to_shards,
)
from .ring_attention import ring_attention, ring_self_attention
from .pipeline import (pipeline_step, partition_stages, PipelineContext,
                       PipelineFallback, pipeline_enabled)
from .spmd import (SpmdContext, SpmdFallback, spmd_enabled, spmd_mesh,
                   model_mesh)
from .elastic import ElasticRuntime, elastic_enabled
from .launcher import initialize_from_env

__all__ = [
    "MeshSpec", "create_mesh", "default_mesh", "current_mesh", "use_mesh",
    "local_mesh",
    "AXIS_DP", "AXIS_FSDP", "AXIS_TP", "AXIS_SP", "AXIS_PP", "AXIS_EP",
    "all_reduce", "all_gather", "reduce_scatter", "ppermute", "barrier",
    "psum_scatter",
    "init_process_group", "process_rank", "process_count", "device_count",
    "KVStoreDistTPUSync",
    "GradSync", "bucket_assign", "bucketing_enabled",
    "Zero1Context", "zero1_enabled",
    "ShardedTrainer", "shard_batch", "replicate",
    "PartitionRules", "infer_param_sharding", "replicated", "flat_shard",
    "pad_to_shards",
    "dp_mesh", "pp_mesh", "mesh_from_env", "axis_size", "has_axis",
    "sharding_constraint",
    "ring_attention", "ring_self_attention",
    "pipeline_step", "partition_stages", "PipelineContext",
    "PipelineFallback", "pipeline_enabled",
    "SpmdContext", "SpmdFallback", "spmd_enabled", "spmd_mesh",
    "model_mesh",
    "ElasticRuntime", "elastic_enabled",
    "initialize_from_env",
]
