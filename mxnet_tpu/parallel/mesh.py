"""Device mesh management.

The reference assigns work to explicit device lists (`executor_group.py:65`
slices the batch over `ctx` lists; `comm.h` builds reduce trees over them;
`gpu_topology.h` solves the link topology). On TPU the topology is a given:
devices form an ICI torus, and XLA lays collectives onto it from a
`jax.sharding.Mesh` — so the mesh IS the context list, and axis names are
the parallelism declaration.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

import jax
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_PP = "pp"
AXIS_EP = "ep"

_STANDARD_AXES = (AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_PP, AXIS_EP)

_state = threading.local()


class MeshSpec:
    """Declarative mesh shape: ordered {axis: size}; -1 once to absorb the
    remaining devices (like a reshape)."""

    def __init__(self, **axes):
        if not axes:
            axes = {AXIS_DP: -1}
        self.axes = dict(axes)

    def resolve(self, n_devices):
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        assert len(wild) <= 1, f"at most one -1 axis, got {wild}"
        fixed = int(np.prod([v for v in sizes.values() if v != -1])) if sizes else 1
        if wild:
            assert n_devices % fixed == 0, \
                f"{n_devices} devices not divisible by fixed axes product {fixed}"
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        assert total == n_devices, \
            f"mesh {sizes} covers {total} devices but {n_devices} are available"
        return sizes


def create_mesh(spec=None, devices=None, **axes):
    """Create a Mesh. ``create_mesh(dp=2, tp=4)`` or ``create_mesh(dp=-1)``.

    Device order follows ``jax.devices()`` — on TPU that enumeration is
    torus-contiguous, so trailing (fastest-varying) axes get the
    shortest ICI hops; put tp/sp innermost, dp outermost.
    """
    if spec is None:
        spec = MeshSpec(**axes)
    elif axes:
        raise ValueError("pass either a MeshSpec or axis kwargs, not both")
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    arr = np.array(devices).reshape(*sizes.values())
    return Mesh(arr, tuple(sizes.keys()))


def local_mesh(**axes):
    """Mesh over this process's addressable devices only."""
    return create_mesh(devices=jax.local_devices(), **(axes or {"dp": -1}))


def default_mesh():
    """The ambient mesh: the entered one, else a 1-D dp mesh over all
    devices (cached)."""
    m = current_mesh()
    if m is not None:
        return m
    cached = getattr(_state, "default", None)
    if cached is None or set(cached.devices.flat) != set(jax.devices()):
        cached = create_mesh(dp=-1)
        _state.default = cached
    return cached


def current_mesh():
    """The innermost mesh entered via ``use_mesh`` (or None)."""
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1]
    return None


@contextlib.contextmanager
def use_mesh(mesh):
    """Make ``mesh`` the ambient mesh (and enter it for jax)."""
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        stack.pop()
