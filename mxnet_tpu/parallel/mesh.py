"""Device mesh management.

The reference assigns work to explicit device lists (`executor_group.py:65`
slices the batch over `ctx` lists; `comm.h` builds reduce trees over them;
`gpu_topology.h` solves the link topology). On TPU the topology is a given:
devices form an ICI torus, and XLA lays collectives onto it from a
`jax.sharding.Mesh` — so the mesh IS the context list, and axis names are
the parallelism declaration.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import getenv, register_env

register_env("MXNET_MESH_SHAPE", "",
             "default device-mesh shape as 'axis=size' pairs, e.g. "
             "'dp=4,tp=2' ('-1' once absorbs the rest); empty = 1-D dp "
             "mesh over every device")

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_PP = "pp"
AXIS_EP = "ep"

_STANDARD_AXES = (AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_PP, AXIS_EP)

_state = threading.local()


class MeshSpec:
    """Declarative mesh shape: ordered {axis: size}; -1 once to absorb the
    remaining devices (like a reshape)."""

    def __init__(self, **axes):
        if not axes:
            axes = {AXIS_DP: -1}
        self.axes = dict(axes)

    def resolve(self, n_devices):
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        assert len(wild) <= 1, f"at most one -1 axis, got {wild}"
        fixed = int(np.prod([v for v in sizes.values() if v != -1])) if sizes else 1
        if wild:
            assert n_devices % fixed == 0, \
                f"{n_devices} devices not divisible by fixed axes product {fixed}"
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        assert total == n_devices, \
            f"mesh {sizes} covers {total} devices but {n_devices} are available"
        return sizes


def create_mesh(spec=None, devices=None, **axes):
    """Create a Mesh. ``create_mesh(dp=2, tp=4)`` or ``create_mesh(dp=-1)``.

    Device order follows ``jax.devices()`` — on TPU that enumeration is
    torus-contiguous, so trailing (fastest-varying) axes get the
    shortest ICI hops; put tp/sp innermost, dp outermost.
    """
    if spec is None:
        spec = MeshSpec(**axes)
    elif axes:
        raise ValueError("pass either a MeshSpec or axis kwargs, not both")
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    arr = np.array(devices).reshape(*sizes.values())
    return Mesh(arr, tuple(sizes.keys()))


def local_mesh(**axes):
    """Mesh over this process's addressable devices only."""
    return create_mesh(devices=jax.local_devices(), **(axes or {"dp": -1}))


def dp_mesh(ndev=None, devices=None):
    """1-D data-parallel mesh over the first ``ndev`` devices (all when
    None/0) — the ZeRO-1 update shard group and the plain-DP default."""
    devices = list(devices if devices is not None else jax.devices())
    if ndev:
        if ndev > len(devices):
            raise ValueError(f"dp_mesh(ndev={ndev}) but only "
                             f"{len(devices)} devices are available")
        devices = devices[:ndev]
    return create_mesh(devices=devices, dp=-1)


def pp_mesh(nstages, devices=None):
    """1-D pipeline mesh over the first ``nstages`` devices — one pipeline
    stage per device (`parallel/pipeline.py`'s default shard group when no
    ambient mesh carries a 'pp' axis)."""
    devices = list(devices if devices is not None else jax.devices())
    if nstages > len(devices):
        raise ValueError(f"pp_mesh(nstages={nstages}) but only "
                         f"{len(devices)} devices are available")
    return create_mesh(devices=devices[:nstages], pp=-1)


def mesh_from_env():
    """Mesh described by ``MXNET_MESH_SHAPE`` ('dp=4,tp=2'), or None.
    A fully-fixed shape smaller than the host's device count takes the
    FIRST matching devices (a '-1' axis absorbs the rest instead)."""
    spec = str(getenv("MXNET_MESH_SHAPE") or "").strip()
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue  # tolerate trailing/doubled commas
        name, eq, size = part.partition("=")
        name = name.strip()
        try:
            if not eq or not name:
                raise ValueError
            axes[name] = int(size)
        except ValueError:
            raise ValueError(
                "MXNET_MESH_SHAPE: expected 'axis=size' pairs like "
                f"'dp=4,tp=2', got {part!r} in {spec!r}") from None
    if not axes:
        return None
    devices = list(jax.devices())
    if -1 not in axes.values():
        total = int(np.prod(list(axes.values())))
        if total < len(devices):
            devices = devices[:total]
    return create_mesh(devices=devices, **axes)


def default_mesh():
    """The ambient mesh: the entered one, else ``MXNET_MESH_SHAPE``, else a
    1-D dp mesh over all devices (cached)."""
    m = current_mesh()
    if m is not None:
        return m
    # keyed on the inputs that determine the result (spec may resolve to a
    # device SUBSET, so the cached mesh's own devices can't be the check)
    key = (str(getenv("MXNET_MESH_SHAPE") or ""),
           tuple(d.id for d in jax.devices()))
    cached = getattr(_state, "default", None)
    if cached is None or getattr(_state, "default_key", None) != key:
        cached = mesh_from_env() or create_mesh(dp=-1)
        _state.default = cached
        _state.default_key = key
    return cached


def axis_size(mesh, axis):
    """Size of ``axis`` in ``mesh`` (1 when absent — the degenerate case
    every sharded path must treat as 'replicated')."""
    return int(mesh.shape.get(axis, 1))


def has_axis(mesh, axis):
    return axis in mesh.shape


def devices_key(mesh):
    """Hashable identity of the mesh's device assignment — part of every
    compile-cache key a sharded program uses, so re-meshing (a different
    device subset or axis order) re-specializes instead of silently
    reusing an executable laid out for other devices."""
    return (tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


def current_mesh():
    """The innermost mesh entered via ``use_mesh`` (or None)."""
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1]
    return None


@contextlib.contextmanager
def use_mesh(mesh):
    """Make ``mesh`` the ambient mesh (and enter it for jax)."""
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        stack.pop()
