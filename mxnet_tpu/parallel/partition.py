"""Parameter partition rules → NamedSharding.

Replaces the reference's manual model parallelism (`group2ctx` ctx-groups,
`symbol.py:1376`, `AssignContext` `graph_executor.cc:920`) with GSPMD
sharding annotations: a small rule table maps parameter names/shapes to
`PartitionSpec`s; XLA propagates the rest.
"""
from __future__ import annotations

import re

import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P


class PartitionRules:
    """Ordered (regex, spec_fn) rules; first match wins.

    ``spec_fn(name, shape) -> PartitionSpec``; plain PartitionSpecs allowed.
    """

    def __init__(self, rules=(), default=P()):
        self._rules = [(re.compile(pat), fn) for pat, fn in rules]
        self._default = default

    def spec_for(self, name, shape):
        for pat, fn in self._rules:
            if pat.search(name):
                spec = fn(name, shape) if callable(fn) else fn
                return _drop_unsized(spec, shape)
        return self._default

    def sharding_for(self, mesh, name, shape):
        return NamedSharding(mesh, _prune_axes(self.spec_for(name, shape), mesh))


def _drop_unsized(spec, shape):
    """Clip the spec to the array's rank."""
    parts = tuple(spec)
    if len(parts) > len(shape):
        parts = parts[:len(shape)]
    return P(*parts)


def _prune_axes(spec, mesh):
    """Remove axes the mesh doesn't have (or that have size 1)."""
    def keep(axis):
        if axis is None:
            return None
        axes = axis if isinstance(axis, tuple) else (axis,)
        kept = tuple(a for a in axes if a in mesh.shape and mesh.shape[a] > 1)
        if not kept:
            return None
        return kept if len(kept) > 1 else kept[0]
    return P(*[keep(a) for a in tuple(spec)])


def replicated(mesh):
    """Fully-replicated NamedSharding — the reference's per-device weight
    copies (`kvstore_local.h`) expressed as a GSPMD layout."""
    return NamedSharding(mesh, P())


def flat_shard(mesh, axis="dp"):
    """1-D sharding of a flat buffer over one mesh axis (falls back to the
    mesh's first axis when ``axis`` is absent) — the layout of a ZeRO-1
    optimizer-state shard and of a reduce-scattered gradient bucket."""
    if axis not in mesh.shape:
        axis = mesh.axis_names[0]
    return NamedSharding(mesh, P(axis))


def pad_to_shards(n, nshards):
    """Trailing zero-padding that makes an ``n``-element flat buffer
    divisible into ``nshards`` equal slices (uneven-shard padding)."""
    nshards = max(int(nshards), 1)
    return (-int(n)) % nshards


def nbytes_on_device(arr, device=None):
    """Bytes of ``arr`` resident on one device (default: the first device
    holding a shard) — the per-replica memory a sharded allocation costs,
    measurable without trusting the sharding annotation."""
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return int(arr.size) * arr.dtype.itemsize
    if device is None:
        device = shards[0].device
    return sum(int(np.prod(s.data.shape)) * arr.dtype.itemsize
               for s in shards if s.device == device)


def infer_param_sharding(mesh, name, shape, fsdp_min_size=2 ** 16):
    """Shape-only sharding heuristic for ONE parameter (this module's
    original rule-table companion). The fused-step/serving planner is
    the GRAPH-AWARE `parallel.spmd.infer_param_sharding` (same policy
    intent, but it walks the symbol's matmul topology for the Megatron
    column/row alternation and returns a {name: PartitionSpec} plan) —
    prefer it whenever a Symbol is available.

    Default sharding policy for a parameter:

    * 'tp' in mesh: matmul weights (2-D) split on the output dim for
      column-parallel layers (Megatron-style; rule tables override for
      row-parallel second matmuls).
    * 'fsdp' in mesh: shard the largest divisible dim of big params
      (ZeRO-3 / "How to Scale Your Model" fully-sharded recipe).
    * else replicate — exactly the reference's data-parallel layout
      (weights replicated per device, `kvstore_local.h`).
    """
    parts = [None] * len(shape)
    if "tp" in mesh.shape and mesh.shape["tp"] > 1 and len(shape) >= 2:
        tp = mesh.shape["tp"]
        if shape[0] % tp == 0:
            parts[0] = "tp"
    if "fsdp" in mesh.shape and mesh.shape["fsdp"] > 1 and \
            int(np.prod(shape)) >= fsdp_min_size:
        fsdp = mesh.shape["fsdp"]
        for i in range(len(shape)):
            if parts[i] is None and shape[i] % fsdp == 0:
                parts[i] = "fsdp"
                break
    return NamedSharding(mesh, P(*parts))
