"""Ring attention: exact attention over sequences sharded across chips.

The reference has NO sequence parallelism (SURVEY.md §5 — its long-sequence
tools are bucketing + truncated BPTT); this is the TPU-first extension the
ICI torus makes natural. Algorithm (Liu et al., blockwise ring attention):
shard the sequence over the 'sp' mesh axis; each device holds its Q block
permanently and passes its K/V block around the ring with `ppermute`
(one ICI hop per step), accumulating attention with the numerically-stable
streaming-softmax update. Peak memory O(seq/n) per chip, compute overlaps
communication (XLA pipelines the ppermute with the matmuls).

Used inside `shard_map` over a mesh with an 'sp' axis; `ring_self_attention`
is the eager/sharded convenience wrapper.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compile_cache import CompileCache
from . import mesh as mesh_mod
from .mesh import AXIS_SP, default_mesh

# one jitted shard_map program per (mesh, axis, size, causal, scale) —
# named so `compile_cache.named_stats("ring_attention")` answers "did a
# long-sequence step recompile?" (this was an anonymous lru_cache, the
# exact silent-recompile class tpulint's executable-cache rule now flags)
_ring_cache = CompileCache("ring_attention")


def _block_attn(q, k, v, bias=None, scale=None):
    """One Q-block × K/V-block partial attention.

    Returns (numerator, row max, row sum-exp) for streaming combination.
    q: [B, Lq, H, D], k/v: [B, Lk, H, D].
    """
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    # fp32 softmax: scores, max and sum-exp accumulate in float32 even when
    # q/k/v are bfloat16 (matches the module's stated design; avoids
    # precision loss accumulating l over many K blocks)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)                    # [B,H,Lq,1]
    p = jnp.exp(s - lax.stop_gradient(m))
    l = jnp.sum(p, axis=-1, keepdims=True)                    # [B,H,Lq,1]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)   # [B,Lq,H,D]
    return o, m, l


def _combine(o1, m1, l1, o2, m2, l2):
    """Merge two streaming-softmax partials (flash-attention rescale).

    The max-shift must be gradient-inert everywhere: _block_attn computes
    p = exp(s - stop_gradient(m)), so the rescale factors here must also be
    stop-gradiented or spurious gradients flow through each block's argmax
    (the shift cancels exactly in the true softmax, so killing its gradient
    is exact, same as standard flash/ring attention backward).
    """
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(lax.stop_gradient(m1) - lax.stop_gradient(m))
    a2 = jnp.exp(lax.stop_gradient(m2) - lax.stop_gradient(m))
    l = l1 * a1 + l2 * a2
    o = o1 * _bhql_to_bqhl(a1).astype(o1.dtype) + o2 * _bhql_to_bqhl(a2).astype(o2.dtype)
    return o, m, l


def _bhql_to_bqhl(x):
    # [B,H,Lq,1] scaling factor applied to [B,Lq,H,D]
    return jnp.transpose(x, (0, 2, 1, 3))


def _hop_fn(scale):
    """Per-hop block attention: the fused Pallas kernel on the TPU backend
    (VMEM-resident QK^T/softmax/PV while K/V ride the ICI ring; exact
    recomputed backward), the XLA blockwise path elsewhere. Same policy
    knobs as the transformer's local attention (MXNET_PALLAS_ATTENTION /
    MXNET_PALLAS_INTERPRET)."""
    import os

    flag = os.environ.get("MXNET_PALLAS_ATTENTION")
    if flag is not None:
        enabled = flag == "1"
    else:
        try:
            enabled = jax.default_backend() == "tpu"
        except Exception:  # noqa: BLE001
            enabled = False
    if enabled:
        try:
            from ..ops.pallas_attention import block_partials_pallas

            interpret = os.environ.get("MXNET_PALLAS_INTERPRET") == "1"
            return lambda q, k, v, bias: block_partials_pallas(
                q, k, v, bias, scale, interpret=interpret)
        except Exception:  # noqa: BLE001 — pallas unavailable
            pass
    return lambda q, k, v, bias: _block_attn(q, k, v, bias, scale)


def ring_attention(q, k, v, axis_name, axis_size, causal=False, scale=None,
                   q_offset=None):
    """Exact attention where K/V circulate the 'sp' ring.

    All inputs are the LOCAL sequence shards: q [B, Lq, H, D], k/v
    [B, Lk, H, D]. Must run inside `shard_map` with mesh axis `axis_name`.
    ``causal`` masks with GLOBAL positions (shard i owns rows
    [i*Lq, (i+1)*Lq)).
    """
    my_idx = lax.axis_index(axis_name)
    lq = q.shape[1]
    lk = k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    q_pos_base = (my_idx if q_offset is None else q_offset) * lq

    def bias_for(kv_idx):
        if not causal:
            return None
        q_pos = q_pos_base + jnp.arange(lq)[:, None]          # [Lq,1]
        k_pos = kv_idx * lk + jnp.arange(lk)[None, :]          # [1,Lk]
        mask = q_pos >= k_pos
        # finite mask constant: -inf breaks the streaming combine when a
        # whole K/V block is masked (max would be -inf ⇒ inf-inf = nan);
        # -1e30 makes fully-masked blocks drop out with weight exp(-1e30-m)=0
        return jnp.where(mask, 0.0, -1e30)[None, None]         # [1,1,Lq,Lk]

    from .collectives import ring_shift

    block = _hop_fn(scale)

    o, m, l = block(q, k, v, bias_for(my_idx))

    def body(i, carry):
        o, m, l, k, v = carry
        # one ICI hop: the shared ring primitive (collectives.ring_shift),
        # not a privately-built permutation table
        k = ring_shift(k, axis_name, axis_size)
        v = ring_shift(v, axis_name, axis_size)
        kv_idx = (my_idx - i - 1) % axis_size
        o2, m2, l2 = block(q, k, v, bias_for(kv_idx))
        o, m, l = _combine(o, m, l, o2, m2, l2)
        return o, m, l, k, v

    o, m, l, _, _ = lax.fori_loop(0, axis_size - 1, body, (o, m, l, k, v))
    return (o / _bhql_to_bqhl(l).astype(o.dtype)).astype(q.dtype)


def ring_self_attention(q, k, v, mesh=None, axis_name=AXIS_SP, causal=False,
                        scale=None):
    """Sharded entry point: q/k/v are GLOBAL [B, L, H, D] arrays (or numpy);
    the sequence dim is sharded over `axis_name` and ring attention runs as
    one jitted SPMD program.

    Mesh resolution goes through the shared substrate (`mesh.default_mesh`
    honors `use_mesh` and `MXNET_MESH_SHAPE`, so e.g. 'dp=2,sp=4' composes
    the same way zero1/pipeline resolve their axes); the degenerate-axis
    check uses `mesh.axis_size` — absent axis == size 1 == replicated."""
    from .collectives import shard_map

    mesh = mesh or default_mesh()
    n = mesh_mod.axis_size(mesh, axis_name)
    if n == 1:
        # no (or size-1) sequence axis — plain attention
        qj = jnp.asarray(q)
        o, m, l = _block_attn(qj, jnp.asarray(k), jnp.asarray(v),
                              _full_causal_bias(q.shape[1], k.shape[1]) if causal else None,
                              scale)
        return (o / _bhql_to_bqhl(l).astype(o.dtype)).astype(qj.dtype)

    fn = _sharded_ring_fn(mesh, axis_name, n, causal, scale)
    spec = NamedSharding(mesh, P(None, axis_name))
    q = jax.device_put(jnp.asarray(q), spec)
    k = jax.device_put(jnp.asarray(k), spec)
    v = jax.device_put(jnp.asarray(v), spec)
    with mesh:
        return fn(q, k, v)


def _full_causal_bias(lq, lk):
    mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
    return jnp.where(mask, 0.0, -1e30)[None, None]


def _sharded_ring_fn(mesh, axis_name, axis_size, causal, scale):
    def build():
        from .collectives import shard_map

        spec = P(None, axis_name)

        def body(q, k, v):
            return ring_attention(q, k, v, axis_name, axis_size, causal,
                                  scale)

        return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec))

    return _ring_cache.get_or_build(
        (mesh, axis_name, axis_size, causal, scale), build)
