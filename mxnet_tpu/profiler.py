"""Profiler — chrome://tracing JSON output + jax profiler bridge.

Parity: `python/mxnet/profiler.py` (set_config :33, start/stop, dump :122,
dumps :151, scoped Task/Frame/Event/Counter/Marker) over the reference's
`src/profiler/profiler.h:256`.

TPU-native: device-side op timing comes from jax's XLA profiler
(``jax.profiler.start_trace`` → xplane/perfetto, viewable in TensorBoard or
chrome://tracing); host-side scopes are recorded here and written as chrome
trace events, matching the reference's output format.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "Task", "Frame", "Event", "Counter", "Marker", "scope",
           "record_op", "aggregate_stats", "dumps_aggregate",
           "dropped_events", "peek_json", "peek_doc"]

_config = {"filename": "profile.json", "profile_all": False, "aggregate_stats": False}
_events = []
_dropped = 0  # events discarded once _events hit max_events
_unmirrored = 0  # drops not yet flushed into the telemetry counter
_MAX_EVENTS_DEFAULT = 1 << 20
_lock = threading.Lock()
_running = False
_jax_trace_dir = None


def set_config(**kwargs):
    """Parity `profiler.py:33`. Recognized: filename, profile_(all|symbolic|
    imperative|memory|api), aggregate_stats, continuous_dump, max_events
    (event-buffer cap; overflow counts into `dropped_events()`)."""
    _config.update(kwargs)


def start(profile_process="worker"):
    global _running, _jax_trace_dir
    _running = True
    fname = _config.get("filename", "profile.json")
    trace_dir = os.path.splitext(fname)[0] + "_xla"
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
        _jax_trace_dir = trace_dir
    except Exception:
        _jax_trace_dir = None


def stop(profile_process="worker"):
    global _running
    _running = False
    if _jax_trace_dir is not None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass


def pause(profile_process="worker"):
    global _running
    _running = False


def resume(profile_process="worker"):
    global _running
    _running = True


def _emit(name, ph, cat="host", ts=None, args=None, dur=None):
    global _dropped, _unmirrored
    if not _running:
        return
    ev = {"name": name, "ph": ph, "cat": cat, "pid": os.getpid(),
          "tid": threading.get_ident(), "ts": ts if ts is not None else time.time() * 1e6}
    if args:
        ev["args"] = args
    if dur is not None:
        ev["dur"] = dur
    with _lock:
        # bounded buffer: a profiler left running for a long job must not
        # eat the heap — overflow is counted, never silent. Only the
        # count moves here: once the buffer is full the drop path IS the
        # steady state, so it must not take the telemetry registry lock
        # per event — _mirror_drops() flushes the total at capture time
        if len(_events) >= _config.get("max_events", _MAX_EVENTS_DEFAULT):
            _dropped += 1
            _unmirrored += 1
            return
        _events.append(ev)


def is_running():
    return _running


def dropped_events():
    """Events discarded since the last reset because the buffer was full."""
    return _dropped


def record_op(name, dur_us, cat="dispatch"):
    """Record one op invocation of `dur_us` microseconds — the role of the
    engine's ProfileOperator wrap (`threaded_engine.h:353-362`), called by
    the nd dispatch layer when profiling is on. Default category is
    "dispatch": jax dispatch is async, so the duration is HOST dispatch
    cost, not device execution. The dispatch layer passes cat="operator"
    only when it actually blocked on the result (`profile_all` /
    `profile_sync`), making the label tell the truth about what was
    measured."""
    _emit(name, "X", cat, ts=time.time() * 1e6 - dur_us, dur=dur_us)


def aggregate_stats(events=None):
    """Per-name aggregate over recorded duration events: {category:
    {name: (count, total_ms, min_ms, max_ms)}} — the
    `aggregate_stats.cc` AggregateStats role. ``events`` aggregates a
    caller-captured snapshot (dumps() uses it to capture+reset atomically)
    instead of the live buffer."""
    stats = {}
    if events is None:
        with _lock:
            events = list(_events)
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        cat = ev.get("cat", "host")
        ms = ev["dur"] / 1e3
        cnt, tot, mn, mx = stats.setdefault(cat, {}).get(
            ev["name"], (0, 0.0, float("inf"), 0.0))
        stats[cat][ev["name"]] = (cnt + 1, tot + ms, min(mn, ms), max(mx, ms))
    return stats


def dumps_aggregate(sort_by="total", ascending=False, events=None):
    """Render the aggregate per-op summary table — the terminal-readable
    output of the reference's `MXAggregateProfileStatsPrint`
    (`aggregate_stats.cc`). sort_by: total|avg|min|max|count."""
    key_idx = {"count": 0, "total": 1, "min": 2, "max": 3, "avg": 4}
    if sort_by not in key_idx:
        raise ValueError(f"sort_by must be one of {sorted(key_idx)}")
    lines = ["", "Profile Statistics:"]
    hdr = (f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
           f"{'Min Time (ms)':>16}{'Max Time (ms)':>16}{'Avg Time (ms)':>16}")
    for cat, names in sorted(aggregate_stats(events).items()):
        lines.append("")
        lines.append(cat)
        lines.append("=" * len(cat))
        lines.append(hdr)
        lines.append(f"{'----':<40}{'-----------':>12}{'---------':>14}"
                     f"{'-------------':>16}{'-------------':>16}"
                     f"{'-------------':>16}")
        rows = []
        for name, (cnt, tot, mn, mx) in names.items():
            rows.append((name, cnt, tot, mn, mx, tot / cnt))
        idx = key_idx[sort_by]
        rows.sort(key=lambda r: r[1 + idx] if sort_by != "count" else r[1],
                  reverse=not ascending)
        for name, cnt, tot, mn, mx, avg in rows:
            lines.append(f"{name[:39]:<40}{cnt:>12}{tot:>14.4f}{mn:>16.4f}"
                         f"{mx:>16.4f}{avg:>16.4f}")
    return "\n".join(lines) + "\n"


def _reset_events():
    global _dropped
    _events.clear()
    _dropped = 0


def _mirror_drops():
    """Flush accumulated drop counts into the monotonic
    ``profiler.dropped_events`` telemetry counter — called at capture
    time (NOT per dropped event) so silent event loss still shows in
    every telemetry dump without the drop path taking the registry lock."""
    global _unmirrored
    with _lock:
        n = _unmirrored
        _unmirrored = 0
    if n:
        try:
            from . import telemetry

            telemetry.counter("profiler.dropped_events").inc(n)
        except Exception:  # noqa: BLE001
            pass


def _capture(reset=False):
    """Snapshot (events, dropped); ``reset`` clears the buffer in the SAME
    critical section, so an event emitted concurrently is either in this
    capture or in the next one — never silently dropped between two lock
    takes. Span-tracing events (`mxnet_tpu.tracing`) merge here so one
    trace file carries host scopes, op dispatch AND request/step span
    trees; on reset the tracing buffer is drained with the same
    exactly-once contract."""
    with _lock:
        events = list(_events)
        dropped = _dropped
        if reset:
            _reset_events()
    _mirror_drops()
    try:
        from . import tracing

        t_events, t_dropped = tracing.take_events(reset=reset)
        events = events + t_events
        dropped += t_dropped
    except Exception:  # noqa: BLE001 — the merge is additive
        pass
    return events, dropped


def _render_doc(events, dropped):
    """The chrome-trace document (dict) with the telemetry registry's
    counter events merged in (same timeline as the host scopes and the
    XLA trace) and the dropped-event count in otherData."""
    try:  # telemetry merge is additive — never break a dump
        from . import telemetry

        if telemetry._enabled and (events or _running):
            events = events + telemetry.trace_counter_events()
    except Exception:  # noqa: BLE001
        pass
    try:  # health journal merge: runtime events (evictions, drains,
        # watchdog firings) as chrome-trace instant marks on the same
        # timeline as spans and counters
        from . import health

        if health._enabled:
            events = events + health.trace_instant_events()
    except Exception:  # noqa: BLE001
        pass
    doc = {"traceEvents": events}
    other = {}
    if dropped:
        other["dropped_events"] = dropped
    # dist identity for tools/trace_merge.py: which worker wrote this dump
    wid = os.environ.get("MXNET_PROCESS_ID", os.environ.get("DMLC_WORKER_ID"))
    if wid is not None:
        other["worker"] = wid
    if other:
        doc["otherData"] = other
    return doc


def _render_trace(events, dropped):
    return json.dumps(_render_doc(events, dropped), indent=2)


def _trace_json(reset=False):
    return _render_trace(*_capture(reset))


def peek_doc():
    """The current buffer (host scopes + tracing spans + telemetry
    counters) as a chrome-trace dict WITHOUT consuming it — the telemetry
    HTTP endpoint's /trace read (serialize once, no parse-back)."""
    return _render_doc(*_capture(reset=False))


def peek_json():
    """:func:`peek_doc`, serialized."""
    return json.dumps(peek_doc(), indent=2)


def dumps(reset=False, sort_by="total", ascending=False):
    """Reference `profiler.py:151` dumps: the aggregate per-op table when
    `aggregate_stats=True` was configured, else the chrome-trace JSON."""
    if _config.get("aggregate_stats"):
        with _lock:
            evs = list(_events)
            if reset:
                _reset_events()
        _mirror_drops()
        return dumps_aggregate(sort_by, ascending, events=evs)
    return _trace_json(reset=reset)


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace JSON to the configured filename (the
    aggregate table is a dumps() view). ``finished=True`` (the default, the
    reference's contract) resets the event buffer after writing, so
    repeated dumps never duplicate events; ``finished=False`` is a
    continuous mid-run dump that keeps accumulating. A failed write puts
    the captured events back — a bad filename must not destroy the trace
    (retry with a corrected set_config)."""
    global _dropped
    fname = _config.get("filename", "profile.json")
    events, dropped = _capture(reset=finished)
    try:
        out = _render_trace(events, dropped)
        with open(fname, "w") as f:
            f.write(out)
    except BaseException:
        if finished:  # restore: the dump failed, the trace is NOT consumed
            with _lock:
                _events[:0] = events
                _dropped += dropped
        raise


class _Scoped:
    _cat = "host"

    def __init__(self, name, **kwargs):
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.time() * 1e6
        return self

    def stop(self):
        if self._t0 is not None:
            _emit(self.name, "X", self._cat, ts=self._t0, dur=time.time() * 1e6 - self._t0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class Task(_Scoped):
    _cat = "task"

    def __init__(self, domain=None, name="task"):
        super().__init__(name)


class Frame(_Scoped):
    _cat = "frame"

    def __init__(self, domain=None, name="frame"):
        super().__init__(name)


class Event(_Scoped):
    _cat = "event"


class Counter:
    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value
        _emit(self.name, "C", "counter", args={"value": value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope="process"):
        _emit(self.name, "i", "marker", args={"scope": scope})


def scope(name="<unk>", append_mode=True):
    return Event(name)
