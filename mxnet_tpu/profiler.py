"""Profiler — chrome://tracing JSON output + jax profiler bridge.

Parity: `python/mxnet/profiler.py` (set_config :33, start/stop, dump :122,
dumps :151, scoped Task/Frame/Event/Counter/Marker) over the reference's
`src/profiler/profiler.h:256`.

TPU-native: device-side op timing comes from jax's XLA profiler
(``jax.profiler.start_trace`` → xplane/perfetto, viewable in TensorBoard or
chrome://tracing); host-side scopes are recorded here and written as chrome
trace events, matching the reference's output format.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "Task", "Frame", "Event", "Counter", "Marker", "scope",
           "record_op", "aggregate_stats", "dumps_aggregate"]

_config = {"filename": "profile.json", "profile_all": False, "aggregate_stats": False}
_events = []
_lock = threading.Lock()
_running = False
_jax_trace_dir = None


def set_config(**kwargs):
    """Parity `profiler.py:33`. Recognized: filename, profile_(all|symbolic|
    imperative|memory|api), aggregate_stats, continuous_dump."""
    _config.update(kwargs)


def start(profile_process="worker"):
    global _running, _jax_trace_dir
    _running = True
    fname = _config.get("filename", "profile.json")
    trace_dir = os.path.splitext(fname)[0] + "_xla"
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
        _jax_trace_dir = trace_dir
    except Exception:
        _jax_trace_dir = None


def stop(profile_process="worker"):
    global _running
    _running = False
    if _jax_trace_dir is not None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass


def pause(profile_process="worker"):
    global _running
    _running = False


def resume(profile_process="worker"):
    global _running
    _running = True


def _emit(name, ph, cat="host", ts=None, args=None, dur=None):
    if not _running:
        return
    ev = {"name": name, "ph": ph, "cat": cat, "pid": os.getpid(),
          "tid": threading.get_ident(), "ts": ts if ts is not None else time.time() * 1e6}
    if args:
        ev["args"] = args
    if dur is not None:
        ev["dur"] = dur
    with _lock:
        _events.append(ev)


def is_running():
    return _running


def record_op(name, dur_us, cat="operator"):
    """Record one operator execution of `dur_us` microseconds — the role of
    the engine's ProfileOperator wrap (`threaded_engine.h:353-362`): called
    by the nd dispatch layer when profiling is on."""
    _emit(name, "X", cat, ts=time.time() * 1e6 - dur_us, dur=dur_us)


def aggregate_stats():
    """Per-name aggregate over recorded duration events: {category:
    {name: (count, total_ms, min_ms, max_ms)}} — the
    `aggregate_stats.cc` AggregateStats role."""
    stats = {}
    with _lock:
        evs = list(_events)
    for ev in evs:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        cat = ev.get("cat", "host")
        ms = ev["dur"] / 1e3
        cnt, tot, mn, mx = stats.setdefault(cat, {}).get(
            ev["name"], (0, 0.0, float("inf"), 0.0))
        stats[cat][ev["name"]] = (cnt + 1, tot + ms, min(mn, ms), max(mx, ms))
    return stats


def dumps_aggregate(sort_by="total", ascending=False):
    """Render the aggregate per-op summary table — the terminal-readable
    output of the reference's `MXAggregateProfileStatsPrint`
    (`aggregate_stats.cc`). sort_by: total|avg|min|max|count."""
    key_idx = {"count": 0, "total": 1, "min": 2, "max": 3, "avg": 4}
    if sort_by not in key_idx:
        raise ValueError(f"sort_by must be one of {sorted(key_idx)}")
    lines = ["", "Profile Statistics:"]
    hdr = (f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
           f"{'Min Time (ms)':>16}{'Max Time (ms)':>16}{'Avg Time (ms)':>16}")
    for cat, names in sorted(aggregate_stats().items()):
        lines.append("")
        lines.append(cat)
        lines.append("=" * len(cat))
        lines.append(hdr)
        lines.append(f"{'----':<40}{'-----------':>12}{'---------':>14}"
                     f"{'-------------':>16}{'-------------':>16}"
                     f"{'-------------':>16}")
        rows = []
        for name, (cnt, tot, mn, mx) in names.items():
            rows.append((name, cnt, tot, mn, mx, tot / cnt))
        idx = key_idx[sort_by]
        rows.sort(key=lambda r: r[1 + idx] if sort_by != "count" else r[1],
                  reverse=not ascending)
        for name, cnt, tot, mn, mx, avg in rows:
            lines.append(f"{name[:39]:<40}{cnt:>12}{tot:>14.4f}{mn:>16.4f}"
                         f"{mx:>16.4f}{avg:>16.4f}")
    return "\n".join(lines) + "\n"


def dumps(reset=False, sort_by="total", ascending=False):
    """Reference `profiler.py:151` dumps: the aggregate per-op table when
    `aggregate_stats=True` was configured, else the chrome-trace JSON."""
    if _config.get("aggregate_stats"):
        out = dumps_aggregate(sort_by, ascending)
        if reset:
            with _lock:
                _events.clear()
        return out
    with _lock:
        out = json.dumps({"traceEvents": list(_events)}, indent=2)
        if reset:
            _events.clear()
    return out


def dump(finished=True, profile_process="worker"):
    # always the chrome-trace JSON (the aggregate table is a dumps() view)
    fname = _config.get("filename", "profile.json")
    with _lock:
        out = json.dumps({"traceEvents": list(_events)}, indent=2)
    with open(fname, "w") as f:
        f.write(out)


class _Scoped:
    _cat = "host"

    def __init__(self, name, **kwargs):
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.time() * 1e6
        return self

    def stop(self):
        if self._t0 is not None:
            _emit(self.name, "X", self._cat, ts=self._t0, dur=time.time() * 1e6 - self._t0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class Task(_Scoped):
    _cat = "task"

    def __init__(self, domain=None, name="task"):
        super().__init__(name)


class Frame(_Scoped):
    _cat = "frame"

    def __init__(self, domain=None, name="frame"):
        super().__init__(name)


class Event(_Scoped):
    _cat = "event"


class Counter:
    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value
        _emit(self.name, "C", "counter", args={"value": value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope="process"):
        _emit(self.name, "i", "marker", args={"scope": scope})


def scope(name="<unk>", append_mode=True):
    return Event(name)
