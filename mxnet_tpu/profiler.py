"""Profiler — chrome://tracing JSON output + jax profiler bridge.

Parity: `python/mxnet/profiler.py` (set_config :33, start/stop, dump :122,
dumps :151, scoped Task/Frame/Event/Counter/Marker) over the reference's
`src/profiler/profiler.h:256`.

TPU-native: device-side op timing comes from jax's XLA profiler
(``jax.profiler.start_trace`` → xplane/perfetto, viewable in TensorBoard or
chrome://tracing); host-side scopes are recorded here and written as chrome
trace events, matching the reference's output format.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "Task", "Frame", "Event", "Counter", "Marker", "scope"]

_config = {"filename": "profile.json", "profile_all": False, "aggregate_stats": False}
_events = []
_lock = threading.Lock()
_running = False
_jax_trace_dir = None


def set_config(**kwargs):
    """Parity `profiler.py:33`. Recognized: filename, profile_(all|symbolic|
    imperative|memory|api), aggregate_stats, continuous_dump."""
    _config.update(kwargs)


def start(profile_process="worker"):
    global _running, _jax_trace_dir
    _running = True
    fname = _config.get("filename", "profile.json")
    trace_dir = os.path.splitext(fname)[0] + "_xla"
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
        _jax_trace_dir = trace_dir
    except Exception:
        _jax_trace_dir = None


def stop(profile_process="worker"):
    global _running
    _running = False
    if _jax_trace_dir is not None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass


def pause(profile_process="worker"):
    global _running
    _running = False


def resume(profile_process="worker"):
    global _running
    _running = True


def _emit(name, ph, cat="host", ts=None, args=None, dur=None):
    if not _running:
        return
    ev = {"name": name, "ph": ph, "cat": cat, "pid": os.getpid(),
          "tid": threading.get_ident(), "ts": ts if ts is not None else time.time() * 1e6}
    if args:
        ev["args"] = args
    if dur is not None:
        ev["dur"] = dur
    with _lock:
        _events.append(ev)


def dumps(reset=False):
    with _lock:
        out = json.dumps({"traceEvents": list(_events)}, indent=2)
        if reset:
            _events.clear()
    return out


def dump(finished=True, profile_process="worker"):
    fname = _config.get("filename", "profile.json")
    with open(fname, "w") as f:
        f.write(dumps())


class _Scoped:
    _cat = "host"

    def __init__(self, name, **kwargs):
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.time() * 1e6
        return self

    def stop(self):
        if self._t0 is not None:
            _emit(self.name, "X", self._cat, ts=self._t0, dur=time.time() * 1e6 - self._t0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class Task(_Scoped):
    _cat = "task"

    def __init__(self, domain=None, name="task"):
        super().__init__(name)


class Frame(_Scoped):
    _cat = "frame"

    def __init__(self, domain=None, name="frame"):
        super().__init__(name)


class Event(_Scoped):
    _cat = "event"


class Counter:
    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value
        _emit(self.name, "C", "counter", args={"value": value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope="process"):
        _emit(self.name, "i", "marker", args={"scope": scope})


def scope(name="<unk>", append_mode=True):
    return Event(name)
