"""Subgraph framework — pluggable graph partition-and-replace.

Parity: `src/operator/subgraph/subgraph_property.h` (`SubgraphSelector`:77,
`SubgraphProperty`:111), `build_subgraph.cc` (the partition pass), and the
`MXNET_REGISTER_SUBGRAPH_PROPERTY` / `MXNET_SUBGRAPH_BACKEND` plumbing the
MKLDNN and TensorRT backends hang off.

TPU-native role: XLA already fuses elementwise chains, so the payoff here
is STRUCTURAL rewrites XLA cannot do — folding BatchNorm into Convolution
weights, swapping op implementations (INT8 quantization,
`contrib/quantization.py`), or grouping a region into one opaque node.
A selector walks the Symbol DAG growing connected regions; the property
replaces each region with a new node. Default replacement is the opaque
`_subgraph_exec` op whose attribute carries the region as Symbol JSON
(the same convention as the control-flow ops), executed by tracing the
inner graph into the enclosing XLA program.
"""
from __future__ import annotations

import json
import os

from ..base import MXNetError
from .symbol import Symbol, _Node, _topo_order, var as _var

__all__ = ["SubgraphSelector", "SubgraphProperty", "register_subgraph_property",
           "get_subgraph_property", "list_subgraph_backends", "build_subgraph"]


class SubgraphSelector:
    """Decides which nodes join a subgraph (reference
    `subgraph_property.h:77`). The walk starts at a node where
    :meth:`select` is true, then grows along input edges accepted by
    :meth:`select_input` and consumer edges accepted by
    :meth:`select_output`; :meth:`filter` gets the final veto."""

    def select(self, node):
        return False

    def select_input(self, node, input_node):
        return False

    def select_output(self, node, output_node):
        return False

    def filter(self, candidates):
        """Return the (possibly trimmed) list of nodes to keep."""
        return candidates

    def reset(self):
        """Called before each new seed walk."""


class SubgraphProperty:
    """A backend's partition rule + replacement factory (reference
    `subgraph_property.h:111`)."""

    def create_subgraph_selector(self):
        return SubgraphSelector()

    def create_subgraph_node(self, subgraph_sym, input_entries, subgraph_id):
        """Return the replacement Symbol for a region.

        ``subgraph_sym``: the region as a Symbol whose free inputs are
        fresh variables; ``input_entries``: the Symbols from the OUTER
        graph feeding those variables, in the same order; ``subgraph_id``:
        ordinal of this region. The default wraps the region into one
        opaque `_subgraph_exec` node (CreateSubgraphNode role)."""
        from . import symbol as _sym_mod

        # args THEN aux states — the same order build_subgraph hands
        # input_entries over in; _graph_fn resolves either kind by name
        inner_args = (subgraph_sym.list_arguments()
                      + subgraph_sym.list_auxiliary_states())
        attrs = {
            "subgraph": subgraph_sym.tojson(),
            "arg_names": ",".join(inner_args),
            "n_out": len(subgraph_sym._outputs),
        }
        return _sym_mod._apply_op("_subgraph_exec", *input_entries,
                                  name=f"subgraph{subgraph_id}", **attrs)


_PROPERTIES = {}


def register_subgraph_property(backend, prop):
    """MXNET_REGISTER_SUBGRAPH_PROPERTY: register under a backend name.
    ``prop`` may be a SubgraphProperty instance or class."""
    _PROPERTIES[backend] = prop


def get_subgraph_property(backend):
    prop = _PROPERTIES.get(backend)
    if prop is None:
        raise MXNetError(f"unknown subgraph backend '{backend}'; "
                         f"registered: {sorted(_PROPERTIES)}")
    return prop() if isinstance(prop, type) else prop


def list_subgraph_backends():
    return sorted(_PROPERTIES)


# ---------------------------------------------------------------------------
# The partition pass (build_subgraph.cc role)
# ---------------------------------------------------------------------------


def _clone_graph(symbol):
    """Deep-clone the DAG so the rewrite never mutates the user's Symbol."""
    mapping = {}

    def clone(node):
        got = mapping.get(id(node))
        if got is not None:
            return got
        new = _Node(node.op, node.name, dict(node.attrs), [])
        mapping[id(node)] = new
        new.inputs = [(clone(c), i) for c, i in node.inputs]
        return new

    outs = [(clone(n), i) for n, i in symbol._outputs]
    return Symbol(outs)


def _consumers_map(nodes):
    cons = {}
    for n in nodes:
        for pos, (child, oidx) in enumerate(n.inputs):
            cons.setdefault(id(child), []).append((n, pos, oidx))
    return cons


def _reaches(src, targets_ids, block_ids, memo):
    """True if src reaches any node in targets_ids without passing through
    block_ids (DFS along input edges, i.e. from consumers to producers)."""
    key = id(src)
    if key in memo:
        return memo[key]
    if key in targets_ids:
        memo[key] = True
        return True
    if key in block_ids:
        memo[key] = False
        return False
    memo[key] = False  # cycle guard (DAG anyway)
    for child, _ in src.inputs:
        if _reaches(child, targets_ids, block_ids, memo):
            memo[key] = True
            break
    return memo[key]


def build_subgraph(symbol, prop):
    """Partition ``symbol`` with ``prop`` and replace each selected region
    (reference `build_subgraph.cc`). Returns a NEW Symbol; the input is
    untouched."""
    if isinstance(prop, str):
        prop = get_subgraph_property(prop)
    sym = _clone_graph(symbol)
    nodes = sym._nodes()
    consumers = _consumers_map(nodes)

    assigned = set()
    regions = []
    for seed in nodes:
        if seed.is_variable or id(seed) in assigned:
            continue
        selector = prop.create_subgraph_selector()
        selector.reset()
        if not selector.select(seed):
            continue
        region = [seed]
        region_ids = {id(seed)}
        frontier = [seed]
        while frontier:
            cur = frontier.pop()
            for child, _ in cur.inputs:
                if child.is_variable or id(child) in region_ids or \
                        id(child) in assigned:
                    continue
                if selector.select_input(cur, child):
                    region.append(child)
                    region_ids.add(id(child))
                    frontier.append(child)
            for cons, _pos, _oidx in consumers.get(id(cur), ()):
                if id(cons) in region_ids or id(cons) in assigned:
                    continue
                if selector.select_output(cur, cons):
                    region.append(cons)
                    region_ids.add(id(cons))
                    frontier.append(cons)
        region = selector.filter(region)
        region_ids = {id(n) for n in region}
        if not region:
            continue
        # convexity: collapsing the region must not create a cycle — no
        # path from a region output through OUTSIDE nodes back into the
        # region (build_subgraph.cc's cycle check)
        convex = True
        for n in region:
            for cons, _pos, _oidx in consumers.get(id(n), ()):
                if id(cons) in region_ids:
                    continue
                # does this outside consumer feed back into the region?
                # fresh memo per target: _reaches caches per-target results,
                # reuse across different cons would mask cycles
                memo = {}
                for other in nodes:
                    if id(other) in region_ids:
                        for child, _ in other.inputs:
                            if id(child) not in region_ids and \
                                    _reaches(child, {id(cons)}, region_ids, memo):
                                convex = False
                                break
                    if not convex:
                        break
                if not convex:
                    break
            if not convex:
                break
        if not convex:
            continue
        assigned |= region_ids
        regions.append(region)

    if not regions:
        return sym

    for sid, region in enumerate(regions):
        _replace_region(sym, sym._nodes(), _consumers_map(sym._nodes()),
                        region, prop, sid)
    return sym


def _replace_region(sym, nodes, consumers, region, prop, sid):
    region_ids = {id(n) for n in region}
    topo = [n for n in nodes if id(n) in region_ids]  # region in topo order

    # external inputs feeding the region, stable order, dedup
    ext_inputs = []
    ext_index = {}
    for n in topo:
        for child, oidx in n.inputs:
            if id(child) in region_ids:
                continue
            key = (id(child), oidx)
            if key not in ext_index:
                ext_index[key] = len(ext_inputs)
                ext_inputs.append((child, oidx))

    # region outputs consumed outside (or by the symbol's heads)
    head_ids = {(id(n), i) for n, i in sym._outputs}
    ext_outputs = []
    out_index = {}
    for n in topo:
        for i in range(n.num_outputs()):
            used_outside = (id(n), i) in head_ids or any(
                id(c) not in region_ids
                for c, _p, oi in consumers.get(id(n), ()) if oi == i)
            if used_outside and (id(n), i) not in out_index:
                out_index[(id(n), i)] = len(ext_outputs)
                ext_outputs.append((n, i))

    # build the inner symbol: clone region nodes, free inputs → variables.
    # Variable names must be unique so input_entries can be re-aligned with
    # list_arguments() order (what SubgraphProperty implementations see).
    inner_map = {}
    inner_vars = []
    used_names = set()
    for idx, (child, oidx) in enumerate(ext_inputs):
        vname = child.name if child.is_variable else f"{child.name}_out{oidx}"
        if vname in used_names:
            vname = f"{vname}_{idx}"
        used_names.add(vname)
        v = _Node(None, vname)
        inner_vars.append(v)
        inner_map[(id(child), oidx)] = (v, 0)

    def inner_clone(node):
        got = inner_map.get(id(node))
        if got is not None:
            return got
        new = _Node(node.op, node.name, dict(node.attrs), [])
        inner_map[id(node)] = new
        ins = []
        for child, oidx in node.inputs:
            if id(child) in region_ids:
                ins.append((inner_clone(child), oidx))
            else:
                ins.append(inner_map[(id(child), oidx)])
        new.inputs = ins
        return new

    inner_outs = [(inner_clone(n), i) for n, i in ext_outputs]
    inner_sym = Symbol(inner_outs)

    # align the outer entries with the inner symbol's list_arguments()
    # order — THE contract SubgraphProperty implementations rely on
    by_name = {v.name: Symbol([(c, i)])
               for v, (c, i) in zip(inner_vars, ext_inputs)}
    input_entries = [by_name[n] for n in (inner_sym.list_arguments()
                                          + inner_sym.list_auxiliary_states())]
    replacement = prop.create_subgraph_node(inner_sym, input_entries, sid)
    if replacement is None:
        return  # property declined this region (Filter-at-create veto)
    if len(replacement._outputs) != len(ext_outputs):
        raise MXNetError(
            f"subgraph property returned {len(replacement._outputs)} outputs "
            f"for a region with {len(ext_outputs)} external outputs")

    # rewrite outer edges: (region node, out idx) -> replacement entry
    repl = {(id(n), i): replacement._outputs[j]
            for j, (n, i) in enumerate(ext_outputs)}
    for n in sym._nodes():
        if id(n) in region_ids:
            continue
        n.inputs = [repl.get((id(c), i), (c, i)) for c, i in n.inputs]
    sym._outputs = [repl.get((id(n), i), (n, i)) for n, i in sym._outputs]


def apply_env_backend(symbol):
    """Apply `MXNET_SUBGRAPH_BACKEND` if set and registered (the bind-time
    hook, reference `build_subgraph.cc` + executor integration)."""
    backend = os.environ.get("MXNET_SUBGRAPH_BACKEND")
    if not backend or backend in ("NONE", "0"):
        return symbol
    if backend not in _PROPERTIES:
        return symbol
    return build_subgraph(symbol, backend)
