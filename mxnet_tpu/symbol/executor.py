"""Symbolic executor — bind a Symbol into a compiled XLA program.

Parity: `include/mxnet/executor.h` / `src/executor/graph_executor.cc`
(`GraphExecutor::Init`:309, `RunOps`:1302, `Forward`:65, `Backward`:78,
`SimpleBind`:1704) and the python wrapper `python/mxnet/executor.py`.

TPU-native redesign: the reference walks the bound graph node-by-node,
pushing each kernel onto the dependency engine (with bulked segments as an
optimization). Here the WHOLE graph is one pure jax function — built once
from the Symbol DAG over the shared op registry — and `jax.jit` compiles it
per (train-flag, shape signature); XLA owns memory planning (`MXPlanMemory`'s
role) and scheduling. Backward is `jax.vjp` over the same function (the
`MXGradient` pass's role), with the pullback captured during `forward(
is_train=True)` so backward never re-runs the forward.
"""
from __future__ import annotations

import time

import numpy as _np

import jax
import jax.numpy as jnp

from .. import observatory, tracing
from ..base import MXNetError
from ..compile_cache import CompileCache
from ..ops import registry as _reg

__all__ = ["Executor"]

# under MXNET_OVERLAP, only every Nth observed fused step drains for an
# exec_s sample — the rest stay dispatch-only so the overlap lane keeps
# its host work hidden behind the in-flight executable
_OBS_PROBE_PERIOD = 8


def _dispatch_node(node, env, key, train, nidx, gate=None):
    """Evaluate ONE non-variable node into ``env``: registry lookup,
    reserved-attr filtering, ``__opt_in__`` keyword binding, per-node RNG
    fold (``nidx`` — the node's GLOBAL topo index, so any walk over a node
    subset sees the same keys as the whole-graph walk), multi-output
    unpack. The single home of the op-dispatch convention — shared by the
    whole-graph walk below and `parallel.pipeline`'s per-stage walk.
    ``gate``: optional transform applied to every tensor input (the
    pipeline's pad-row mask on loss nodes)."""
    op = _reg.get_op(node.op)
    attrs = {k: v for k, v in node.attrs.items()
             if not k.startswith("__")}
    if op.needs_mode:
        attrs["_train"] = train
    f = _reg.bound_fn(node.op, **attrs)
    ins = [env[(id(c), oi)] for c, oi in node.inputs]
    if gate is not None:
        ins = [gate(x) for x in ins]
    # optional tensor inputs recorded by _apply_op bind by keyword
    opt_in = node.attrs.get("__opt_in__") or ""
    kw_ins = {}
    if opt_in:
        names = opt_in.split(",")
        n_pos = len(ins) - len(names)
        kw_ins = dict(zip(names, ins[n_pos:]))
        ins = ins[:n_pos]
    if op.needs_rng:
        out = f(jax.random.fold_in(key, nidx), *ins, **kw_ins)
    else:
        out = f(*ins, **kw_ins)
    outs = out if isinstance(out, (tuple, list)) else (out,)
    for i, o in enumerate(outs):
        env[(id(node), i)] = o


def _graph_fn(sym, arg_names, aux_names, train):
    """Build the pure function of a Symbol graph:
    fn(key, args_tuple, auxs_tuple) -> (outputs_tuple, aux_updates_tuple)."""
    from .symbol import _topo_order

    nodes = _topo_order([n for n, _ in sym._outputs])
    arg_pos = {n: i for i, n in enumerate(arg_names)}
    aux_pos = {n: i for i, n in enumerate(aux_names)}

    # aux write-back map: aux var node id -> (producer node, output index)
    aux_writer = {}
    for node in nodes:
        if node.is_variable:
            continue
        maux = node.aux_input_indices()
        if not maux:
            continue
        n_user = node.num_outputs() - len(maux)
        for j, in_idx in enumerate(maux):
            if in_idx < len(node.inputs):
                child, _ = node.inputs[in_idx]
                if child.is_variable:
                    aux_writer[id(child)] = (node, n_user + j)

    def fn(key, args, auxs):
        env = {}
        for node in nodes:
            if not node.is_variable:
                continue
            if node.name in arg_pos:
                env[(id(node), 0)] = args[arg_pos[node.name]]
            elif node.name in aux_pos:
                env[(id(node), 0)] = auxs[aux_pos[node.name]]
            else:  # unbound variable — an error caught at bind time
                raise MXNetError(f"variable {node.name} is not bound")
        for nidx, node in enumerate(nodes):
            if node.is_variable:
                continue
            _dispatch_node(node, env, key, train, nidx)
        outputs = tuple(env[(id(n), oi)] for n, oi in sym._outputs)
        aux_new = []
        for node in nodes:
            if node.is_variable and node.name in aux_pos:
                w = aux_writer.get(id(node))
                if w is not None and (id(w[0]), w[1]) in env:
                    aux_new.append(env[(id(w[0]), w[1])])
                else:
                    aux_new.append(env[(id(node), 0)])
        return outputs, tuple(aux_new)

    return fn


class Executor:
    """A bound, compiled Symbol (reference `Executor::Forward/Backward`)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        from ..ndarray import NDArray, zeros

        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()

        self.arg_dict = self._normalize(args, self._arg_names, "args")
        self.aux_dict = self._normalize(aux_states, self._aux_names, "aux_states",
                                        allow_missing=True)

        # grad_req per argument
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in self._arg_names}

        if args_grad is None:
            self.grad_dict = {}
        else:
            self.grad_dict = self._normalize(args_grad, self._arg_names,
                                             "args_grad", allow_missing=True)
        for n in self._arg_names:
            if self._grad_req.get(n, "null") != "null" and n not in self.grad_dict:
                a = self.arg_dict[n]
                self.grad_dict[n] = zeros(a.shape, dtype=a.dtype)

        self.outputs = []
        self._vjp = None
        self._monitor_callback = None

        self._fns = {}
        self._last_fwd_key = None
        # every compiled executable this executor holds, keyed by full shape
        # signature — shape churn (bucketing, unpadded partial batches) shows
        # up as compile.cache_misses instead of silently re-specializing.
        # Bounded: churn that escapes padding caps memory too (oldest out)
        self._cache = CompileCache("executor", maxsize=64)

        # memory census (live views — _data is reassigned every step):
        # weights are the args something backprops into, gradients their
        # bound cotangent buffers. Buffer-level dedup in the census makes
        # double-registration (several executors binding shared weights)
        # count once.
        from .. import memory

        memory.register_provider(
            "weights", self,
            lambda s: [a for n, a in s.arg_dict.items()
                       if s._grad_req.get(n, "null") != "null"])
        memory.register_provider("gradients", self,
                                 lambda s: list(s.grad_dict.values()))

    # -- helpers -------------------------------------------------------------

    def _normalize(self, values, names, what, allow_missing=False):
        from ..ndarray import NDArray, array as nd_array

        out = {}
        if values is None:
            values = {}
        if isinstance(values, (list, tuple)):
            if len(values) != len(names):
                raise MXNetError(f"{what}: expected {len(names)} entries "
                                 f"({names}), got {len(values)}")
            values = dict(zip(names, values))
        for n in names:
            v = values.get(n)
            if v is None:
                if allow_missing:
                    continue
                raise MXNetError(f"{what}: missing value for {n}")
            out[n] = v if isinstance(v, NDArray) else nd_array(v)
        return out

    def _fn(self, train):
        fn = self._fns.get(train)
        if fn is None:
            fn = _graph_fn(self._symbol, self._arg_names, self._aux_names, train)
            self._fns[train] = fn
        return fn

    def _sig(self, args, auxs):
        """Shape/dtype signature of one bound call — the compile-cache key
        (the CachedOp signature-match model, `cached_op.cc:295`). Built
        every call, so it uses hashable dtype objects, not strings."""
        return (tuple((a.shape, a.dtype) for a in args),
                tuple((a.shape, a.dtype) for a in auxs))

    def _jit_fwd(self, train, sig):
        return self._cache.get_or_build(
            ("fwd", train, sig), lambda: jax.jit(self._fn(train)))

    def _jit_fwd_vjp(self, train, sig):
        def build():
            base = self._fn(train)
            diff = tuple(i for i, n in enumerate(self._arg_names)
                         if self._grad_req.get(n, "null") != "null")

            def fwd(key, args, auxs):
                args = list(args)

                def f(*darrs):
                    full = list(args)
                    for i, a in zip(diff, darrs):
                        full[i] = a
                    outputs, aux_new = base(key, tuple(full), auxs)
                    return outputs, aux_new

                outputs, vjp, aux_new = jax.vjp(
                    f, *[args[i] for i in diff], has_aux=True)
                return outputs, aux_new, vjp

            return jax.jit(fwd)

        return self._cache.get_or_build(("fwd_vjp", train, sig), build)

    # -- API -----------------------------------------------------------------

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def set_args(self, **kwargs):
        """Write input values into the bound argument buffers (the feed half
        of ``forward``, shared with the fused train step)."""
        from ..ndarray import NDArray, array as nd_array

        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown argument {k}")
            tgt = self.arg_dict[k]
            src = v if isinstance(v, NDArray) else nd_array(v)
            tgt._data = jnp.asarray(src._data, tgt.dtype)

    def forward(self, is_train=False, **kwargs):
        from .. import random as _random
        from ..ndarray import NDArray

        self.set_args(**kwargs)

        key = _random.next_key()
        args = tuple(self.arg_dict[n]._data for n in self._arg_names)
        auxs = tuple(self.aux_dict[n]._data for n in self._aux_names)

        sig = self._sig(args, auxs)
        if is_train and any(r != "null" for r in self._grad_req.values()):
            outputs, aux_new, vjp = self._jit_fwd_vjp(True, sig)(key, args, auxs)
            self._vjp = vjp
        else:
            outputs, aux_new = self._jit_fwd(bool(is_train), sig)(key, args, auxs)
            self._vjp = None
            # which compiled entry this forward ran — the serving plane's
            # roofline attribution reads it back (observatory.observe)
            self._last_fwd_key = ("fwd", bool(is_train), sig)

        if is_train:
            # aux write-back (moving stats) — reference mutable aux NDArrays
            for n, a in zip(self._aux_names, aux_new):
                self.aux_dict[n]._data = a

        self.outputs = [NDArray(o) for o in outputs]
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, out)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        from ..ndarray import NDArray

        if self._vjp is None:
            raise MXNetError("backward requires forward(is_train=True) first "
                             "(and at least one grad_req != 'null')")
        if out_grads is None:
            cts = tuple(jnp.ones(o.shape, o.dtype) for o in self.outputs)
        else:
            if isinstance(out_grads, (NDArray, _np.ndarray)):
                out_grads = [out_grads]
            cts = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                        for g in out_grads)
        grads = _reg.run_vjp(self._vjp, cts)
        diff_names = [n for n in self._arg_names
                      if self._grad_req.get(n, "null") != "null"]
        for n, g in zip(diff_names, grads):
            req = self._grad_req[n]
            tgt = self.grad_dict[n]
            if req == "write":
                tgt._data = g.astype(tgt.dtype)
            elif req == "add":
                tgt._data = tgt._data + g.astype(tgt.dtype)

    def fused_step(self, optimizer, updater, param_names,
                   grad_sync_fn=None, grad_sync_key=None, zero1=None,
                   pipeline=None, spmd=None):
        """ONE training step — forward, backward (ones cotangents, the
        `backward(out_grads=None)` convention), gradient rescale/clip and
        the optimizer update for every parameter — as a single jitted XLA
        computation, with weight, optimizer-state and aux buffers donated
        so XLA updates them in place.

        This is the bulking limit the engine exists to approach (SURVEY L2):
        the eager path crosses the dispatch boundary once per forward, once
        per backward and ~once per parameter chunk in the update loop; here
        the whole step is one dispatch. The eager path remains the
        correctness reference (test_fused_step.py asserts parity).

        ``param_names`` must be the module's parameter list — updater state
        keys are positions in it, matching the eager ``Module.update``
        indexing. Returns the step outputs (also stored in ``self.outputs``).

        Gradients are consumed INSIDE the computation and never
        materialized: ``grad_dict`` is NOT updated by this path (reading it
        after a fused step sees the previous eager step's values, or the
        zeros from bind). Code that needs per-step gradients — Monitor,
        input grads, custom gradient manipulation — must run the eager
        decomposition (``Module._fused_step_ready`` gates the common cases).

        ``grad_sync_fn`` (a traceable ``grads_tuple -> grads_tuple``, from
        ``KVStore.fused_grad_sync_fn``) is applied to the gradients INSIDE
        the trace, between backward and the optimizer update — the
        cross-replica sum over the bucketed flat grads that the eager path
        dispatches as per-bucket collectives. ``grad_sync_key`` must
        identify the sync layout (store type + bucket cap): it keys the
        compile cache so a layout change re-specializes.

        ``zero1`` (a ``parallel.zero1.Zero1Context``, from Module when
        `MXNET_ZERO1=1`) replaces the replicated per-parameter update with
        the sharded one: gradients are constrained to the dp-sharded flat
        bucket layout (with the upstream cross-replica sum this lowers to
        ReduceScatter), the optimizer runs on each replica's 1/N shard of
        params and state (state lives SHARDED in the context, not in
        ``updater.states``), and the updated shards are allgathered back —
        still one donated-buffer XLA computation per signature.

        ``pipeline`` (a ``parallel.pipeline.PipelineContext``, from Module
        when `MXNET_PIPELINE_STAGES>=2`) swaps the plain graph function
        for the GPipe micro-batch schedule over the 'pp' mesh axis: the
        vjp below then differentiates THROUGH the scan/ppermute schedule
        (the reverse pipeline flow), micro-batch gradients accumulate
        inside the trace, and the grad-sync / ZeRO-1 / optimizer tail
        composes unchanged. Pipelined executables compile under the named
        CompileCache("pipeline") so accounting stays pinned per
        (symbol, shapes, stages, microbatches) key.

        ``spmd`` (a ``parallel.spmd.SpmdContext``, from Module when
        `MXNET_SPMD` is set) shards the program itself per GSPMD: bound
        weights are committed at their planned PartitionSpecs (tp
        column/row alternation, fsdp largest-dim — physical per-device
        residency ~1/N), the batch enters dp(+fsdp)-sharded so data
        parallelism lives INSIDE the program, gradients / updated
        weights / optimizer state are constrained to the same layouts
        (fsdp grads lower to ReduceScatter, state bytes follow the
        weight's 1/N), and XLA's SPMD partitioner propagates the rest —
        forward AND backward are sharded, not just the update. Composes
        with ``zero1`` (the flat update unpacks straight back to the
        planned layouts) and ``pipeline`` (residency placement gathered
        just-in-time inside the schedule). Sharded steps compile under
        the context's named CompileCache("spmd").
        """
        from .. import random as _random
        from ..ndarray import NDArray
        from ..optimizer.optimizer import (_any_donated_deleted,
                                           _restore_counts, _snapshot_counts,
                                           _state_sig, _state_to_jax,
                                           _state_writeback)

        upd = [(i, n) for i, n in enumerate(param_names)
               if self._grad_req.get(n, "null") != "null"]
        indices = [i for i, _ in upd]
        names = [n for _, n in upd]
        name_set = set(names)
        weights = [self.arg_dict[n] for n in names]
        if spmd is not None:
            # one-time physical placement: the bound weight buffers drop
            # to their planned 1/N residency HERE, so the first sharded
            # step already aliases its donated inputs
            spmd.place_params(names, weights)
        if zero1 is not None:
            # sharded state lives in the context (1/N per replica); the
            # per-parameter updater states are not materialized
            zero1.ensure(optimizer, updater, indices, weights)
            states = None
        else:
            updater.ensure_states(indices, weights)
        count_snap = _snapshot_counts(optimizer, indices)
        optimizer._update_count(indices)
        lrs, wds = optimizer._fused_hyperparams(indices)
        if zero1 is None:
            states = [updater.states[i] for i in indices]
            if spmd is not None:
                # state leaves shaped like the weight shard with it —
                # per-device optimizer-state bytes follow the same 1/N
                spmd.place_state_trees(names, states)
            state_sig = tuple(_state_sig(s) for s in states)
            states_arg = [_state_to_jax(s) for s in states]
        else:
            state_sig = zero1.key()
            states_arg = zero1.flat_states

        key = _random.next_key()
        params = tuple(self.arg_dict[n]._data for n in names)
        other_names = [n for n in self._arg_names if n not in name_set]
        others = tuple(self.arg_dict[n]._data for n in other_names)
        auxs = tuple(self.aux_dict[n]._data for n in self._aux_names)

        sig = (tuple(names),
               tuple((a.shape, a.dtype) for a in params),
               tuple((a.shape, a.dtype) for a in others),
               tuple((a.shape, a.dtype) for a in auxs),
               state_sig,
               optimizer._fused_static_key(),
               grad_sync_key,
               pipeline.key() if pipeline is not None else None,
               spmd.key() if spmd is not None else None)

        def build():
            base = pipeline.wrap(self, spmd=spmd) if pipeline is not None \
                else self._fn(True)
            arg_pos = {n: i for i, n in enumerate(self._arg_names)}
            param_pos = [arg_pos[n] for n in names]
            other_pos = [arg_pos[n] for n in other_names]
            opt = optimizer
            n_args = len(self._arg_names)

            def step(key, params, others, auxs, ss, lrs_, wds_, rescale):
                from ..compile_cache import trace_salt

                # salt the HLO: this donated program must never be
                # deserialized by another process (compile_cache.trace_salt)
                rescale = trace_salt(rescale)

                def f(*ps):
                    full = [None] * n_args
                    for p, i in zip(ps, param_pos):
                        full[i] = p
                    for o, i in zip(others, other_pos):
                        full[i] = o
                    return base(key, tuple(full), auxs)

                outputs, vjp, aux_new = jax.vjp(f, *params, has_aux=True)
                cts = tuple(jnp.ones(o.shape, o.dtype) for o in outputs)
                grads = vjp(cts)
                if pipeline is not None and \
                        getattr(pipeline, "grad_correction", 1) > 1:
                    # undo the shard_map replication over non-pp mesh
                    # axes (PipelineContext.grad_correction): the vjp
                    # transpose summed identical per-coordinate copies
                    inv = 1.0 / pipeline.grad_correction
                    grads = tuple(g * jnp.asarray(inv, g.dtype)
                                  for g in grads)
                if grad_sync_fn is not None:
                    # cross-replica gradient sync traced into the step
                    # (bucketed flat psum — KVStore.fused_grad_sync_fn)
                    grads = grad_sync_fn(tuple(grads))
                if spmd is not None:
                    # pin gradients to the planned weight layouts: with
                    # the batch-sharded sum upstream the fsdp constraint
                    # lowers to ReduceScatter (parallel/spmd.py)
                    grads = spmd.constrain_grads(names, grads)
                if zero1 is not None:
                    # sharded weight update: grads constrained to the
                    # dp-sharded flat buckets (sum+constraint lowers to
                    # ReduceScatter), 1/N-shard optimizer step, weights
                    # allgathered back replicated — or straight back to
                    # the spmd layouts when both compose
                    new_ws, new_ss = zero1.traced_update(
                        opt, list(params), list(grads), ss,
                        lrs_, wds_, rescale,
                        unpack_shardings=(spmd.param_shardings(names)
                                          if spmd is not None else None))
                else:
                    new_ws, new_ss = opt.fused_update(
                        list(params), list(grads), ss, lrs_, wds_, rescale)
                    if spmd is not None:
                        # updated weights/state persist at the planned
                        # layouts: donation aliases, residency stays 1/N
                        new_ws = spmd.constrain_params(names, new_ws)
                        new_ss = spmd.constrain_state_trees(names, new_ss)
                return outputs, tuple(new_ws), new_ss, aux_new

            # Donate exactly what will ALIAS (the hlolint donation audit
            # enforces declared == aliased): params + auxs + states on the
            # elementwise-update paths, but under ZeRO-1 the updated
            # weights are SLICES of one all-gathered flat bucket — XLA
            # cannot reliably alias k outputs carved from a single gather
            # result into k separate donated buffers (dumps showed it
            # silently declining for most params), so donating them only
            # risked consuming buffers nothing aliased. The flat sharded
            # state and the aux states update elementwise and alias.
            donate = (3, 4) if zero1 is not None else (1, 3, 4)
            return jax.jit(step, donate_argnums=donate)

        # persistent=False: donated programs must stay OUT of the on-disk
        # XLA cache (deserialized aliasing corrupts the heap — see
        # CompileCache.get_or_build). Pipelined steps compile under the
        # named "pipeline" cache, sharded ones under "spmd" (spmd wins
        # when both compose), so per-config accounting is assertable.
        # The audit tag names the hlolint contract row for the
        # COMPOSITION that actually shaped the program: a zero1 step in
        # the generic executor cache is still audited against the
        # reduce-scatter/all-gather contract (tools/hlolint/contracts.py).
        if spmd is not None:
            cache, audit = spmd.cache, "spmd"
        elif pipeline is not None:
            cache, audit = pipeline.cache, "pipeline"
        elif zero1 is not None:
            cache, audit = self._cache, "zero1"
        else:
            cache, audit = self._cache, "fused_step"
        fn = cache.get_or_build(("fused_step", sig), build,
                                persistent=False, audit=audit)
        call_args = [key, params, others, auxs, states_arg,
                     jnp.asarray(lrs, jnp.float32),
                     jnp.asarray(wds, jnp.float32),
                     jnp.float32(optimizer.rescale_grad)]
        if spmd is not None:
            # params/feeds/state onto the mesh at their PLANNED layouts
            # (steady state is a no-op — they come back placed); the
            # zero1 flat state is already dp-sharded and rides untouched
            call_args[1] = tuple(spmd.put(n, a)
                                 for n, a in zip(names, params))
            call_args[2] = tuple(spmd.put(n, a)
                                 for n, a in zip(other_names, others))
            call_args[3] = tuple(spmd.put_replicated(a) for a in auxs)
            # (non-zero1 state leaves were already device_put at the
            # weight's layout by place_state_trees above)
            for i in (0, 5, 6, 7):
                call_args[i] = jax.tree_util.tree_map(spmd.put_replicated,
                                                      call_args[i])
        elif zero1 is not None:
            # everything but the (already-sharded) state enters the mesh
            # replicated; steady state is a no-op for weights/aux (they
            # come back replicated), feeds broadcast here once per step
            put = zero1.put_replicated
            call_args = [jax.tree_util.tree_map(put, a) if i != 4 else a
                         for i, a in enumerate(call_args)]
        elif pipeline is not None:
            # same replication discipline onto the pp mesh: donated
            # buffers must already live replicated on the mesh or the
            # donation silently degrades to a copy
            put = pipeline.put_replicated
            call_args = [jax.tree_util.tree_map(put, a) for a in call_args]
        obs = observatory._enabled
        t_obs = time.perf_counter() if obs else 0.0
        try:
            with tracing.span("fused.dispatch", cat="train",
                              params=len(names),
                              zero1=zero1 is not None,
                              pipeline=pipeline is not None):
                outputs, new_ws, new_ss, aux_new = fn(*call_args)
            if obs:
                # device-busy window for the roofline's host-gap: drain
                # the step and name the executable that ran so attribution
                # can pull its FLOPs/bytes lazily. Under the async overlap
                # lane (MXNET_OVERLAP=1) a per-step drain would serialize
                # exactly the host work the lane exists to hide, so only a
                # PERIODIC probe step drains for an exec_s sample — the
                # EWMA keeps the roofline's exec estimate fresh while the
                # other steps stay dispatch-only (their wall comes from
                # the fit loop's observe).
                from ..io import staging as _staging

                self._obs_probe = getattr(self, "_obs_probe", 0) + 1
                if not _staging.overlap_enabled() or \
                        self._obs_probe % _OBS_PROBE_PERIOD == 1:
                    jax.block_until_ready(outputs)
                    observatory.observe("step", cache,
                                        ("fused_step", sig),
                                        exec_s=time.perf_counter() - t_obs)
                else:
                    # keep the cache/key naming current without a sync
                    observatory.observe("step", cache, ("fused_step", sig))
        except Exception as e:
            donated = [w._data for w in weights]
            if zero1 is not None:
                # the sharded flat state (donated via states_arg) is the
                # only copy once dirty — a consumed state buffer is as
                # fatal as a consumed weight
                donated += jax.tree_util.tree_leaves(zero1.flat_states or [])
            if _any_donated_deleted(donated):
                # donated inputs were consumed before execution failed —
                # the bound weights/states are unrecoverable in-process;
                # say so instead of a later "Array deleted" crash
                raise MXNetError(
                    "fused train step failed mid-execution; weight/"
                    "optimizer-state buffers were donated and may be "
                    "invalidated — restore from the last checkpoint before "
                    f"continuing ({e!r})") from e
            # trace/compile failed BEFORE any buffer was consumed: weights
            # are intact — undo the count bump so the caller's eager
            # fallback doesn't double-count the step, and let the original
            # error through (Module.fused_step turns it into a fallback)
            _restore_counts(optimizer, count_snap)
            raise

        for n, w in zip(names, new_ws):
            self.arg_dict[n]._data = w
        if zero1 is not None:
            zero1.flat_states = new_ss
            zero1.dirty = True
        else:
            for s, ns in zip(states, new_ss):
                _state_writeback(s, ns)
        for n, a in zip(self._aux_names, aux_new):
            self.aux_dict[n]._data = a
        self._vjp = None  # grads were consumed inside the step
        self.outputs = [NDArray(o) for o in outputs]
        if pipeline is not None:
            pipeline.record_step()
        if spmd is not None:
            spmd.record_step(names, weights)
        return self.outputs

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        from ..ndarray import NDArray

        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = jnp.asarray(
                    v._data if isinstance(v, NDArray) else v,
                    self.arg_dict[k].dtype)
            elif not allow_extra_params:
                raise MXNetError(f"unknown arg {k}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._data = jnp.asarray(
                    v._data if isinstance(v, NDArray) else v,
                    self.aux_dict[k].dtype)
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes (cheap — jit re-specializes)."""
        from ..ndarray import zeros

        new_shapes = dict(kwargs)
        arg_shapes, _, aux_shapes = self._symbol.infer_shape_partial(**{
            k: v for k, v in new_shapes.items() if k in self._arg_names})
        args = {}
        for n, s in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if s is not None and tuple(cur.shape) != tuple(s):
                args[n] = zeros(s, dtype=cur.dtype)
            else:
                args[n] = cur
        auxs = {}
        for n, s in zip(self._aux_names, aux_shapes):
            cur = self.aux_dict[n]
            if s is not None and tuple(cur.shape) != tuple(s):
                auxs[n] = zeros(s, dtype=cur.dtype)
            else:
                auxs[n] = cur
        new = Executor(self._symbol, self._ctx, args=args,
                       grad_req=self._grad_req, aux_states=auxs)
        # an installed monitor must survive the rebind (it also gates the
        # fused-step fallback in Module._fused_step_ready)
        new._monitor_callback = self._monitor_callback
        return new

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-output monitor (reference
        `MXExecutorSetMonitorCallbackEX`, `graph_executor.cc:115`)."""
        self._monitor_callback = callback

    def debug_str(self):
        return self._symbol.debug_str()
