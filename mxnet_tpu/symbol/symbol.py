"""Symbol — the declarative graph IR (reference L5a frontend half).

Parity: `python/mxnet/symbol/symbol.py` (composition, `infer_shape`,
`tojson`/`load`, `simple_bind`:1376) over the C++ nnvm Symbol
(`3rdparty/tvm/nnvm`, `src/c_api/c_api_symbolic.cc`).

TPU-native redesign: the reference lowers Symbol → nnvm Graph → GraphExecutor
(`src/executor/graph_executor.cc:309`) which replays node kernels through the
dependency engine. Here a Symbol is a lightweight python DAG over the SAME op
registry the imperative path uses (`ops/registry.py`); binding compiles the
whole graph into ONE cached XLA executable (`executor.py`) — graph passes,
memory planning and scheduling all belong to XLA. The JSON wire format is
kept MXNet-compatible (`nodes`/`arg_nodes`/`heads`) so checkpoints
(`model.save_checkpoint` → `prefix-symbol.json`) and `HybridBlock.export` /
`SymbolBlock.imports` round-trip.
"""
from __future__ import annotations

import ast
import inspect
import json

import numpy as _np

from ..base import MXNetError
from .. import name as _name_mod
from .. import attribute as _attribute
from ..ops import registry as _reg

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]

_MXNET_VERSION = 10500  # wire-format version stamp (reference libinfo 1.5.0)


def _op_input_spec(op):
    """(required_names, optional_name, varargs, aux_indices) for an op fn.

    Tensor inputs are the fn's positional-no-default params (minus the rng
    key); a `*maybe_x` varargs declares ONE optional trailing input named x
    (the reference's no_bias-style optionals); a varargs named `args`
    accepts any number of inputs (UpSampling/Concat style).
    """
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return ["data"], None, True, ()
    required, optional, open_varargs = [], None, False
    for p in sig.parameters.values():
        if p.kind is inspect.Parameter.POSITIONAL_OR_KEYWORD and \
                p.default is inspect.Parameter.empty:
            required.append(p.name)
        elif p.kind is inspect.Parameter.VAR_POSITIONAL:
            if p.name.startswith("maybe_"):
                optional = p.name[len("maybe_"):]
            else:
                open_varargs = True
    if op.needs_rng and required and required[0] == "key":
        required = required[1:]
    aux = () if callable(op.mutate_aux) else tuple(op.mutate_aux or ())
    return required, optional, open_varargs, aux


class _Node:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "_id")

    def __init__(self, op, name, attrs=None, inputs=None):
        self.op = op                      # op name string or None (variable)
        self.name = name
        self.attrs = dict(attrs or {})    # python-typed values
        self.inputs = list(inputs or ())  # [(node, out_index)]

    @property
    def is_variable(self):
        return self.op is None

    def num_outputs(self):
        if self.is_variable:
            return 1
        op = _reg.get_op(self.op)
        return op.n_out({k: v for k, v in self.attrs.items()})

    def aux_input_indices(self):
        if self.is_variable:
            return ()
        aux = _reg.get_op(self.op).mutate_aux
        if callable(aux):
            aux = aux({k: v for k, v in self.attrs.items()})
        return tuple(aux or ())


def _topo_order(head_nodes):
    """Post-order DFS (stable, iterative) over the DAG."""
    order, seen = [], set()
    stack = [(n, False) for n in reversed(head_nodes)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in seen:
            continue
        if expanded:
            seen.add(id(node))
            order.append(node)
        else:
            stack.append((node, True))
            for child, _ in reversed(node.inputs):
                if id(child) not in seen:
                    stack.append((child, False))
    return order


class Symbol:
    """An immutable multi-output handle into the graph."""

    def __init__(self, outputs):
        # list of (node, out_index)
        self._outputs = list(outputs)

    # -- identity ------------------------------------------------------------

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        n = self.name
        return f"<Symbol {n if n else 'Grouped'}>"

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise ValueError(f"no output named {index}; outputs: {names}")
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def get_internals(self):
        """Symbol whose outputs are EVERY internal node output
        (reference symbol.py get_internals)."""
        outs = []
        for node in _topo_order([n for n, _ in self._outputs]):
            outs.extend((node, i) for i in range(node.num_outputs()))
        return Symbol(outs)

    def get_children(self):
        nodes = {id(n): n for n, _ in self._outputs}
        children = []
        for n in nodes.values():
            children.extend(n.inputs)
        return Symbol(children) if children else None

    # -- attrs ---------------------------------------------------------------

    def attr(self, key):
        if len(self._outputs) == 1:
            v = self._outputs[0][0].attrs.get(key)
            return None if v is None else str(v)
        return None

    def list_attr(self):
        if len(self._outputs) != 1:
            return {}
        return {k: str(v) for k, v in self._outputs[0][0].attrs.items()
                if k.startswith("__") or not _is_op_param(self._outputs[0][0], k)}

    def attr_dict(self):
        out = {}
        for node in _topo_order([n for n, _ in self._outputs]):
            if node.attrs:
                out[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for n, _ in self._outputs:
            n.attrs.update(kwargs)

    # -- listing -------------------------------------------------------------

    def _nodes(self):
        return _topo_order([n for n, _ in self._outputs])

    def _arg_aux_split(self):
        """Variables in graph order, split into (args, auxs) by whether any
        consumer uses them in an aux slot (reference FMutateInputs rule,
        `imperative.cc` ndinputs vs auxs)."""
        aux_ids = set()
        nodes = self._nodes()
        for node in nodes:
            for ai in node.aux_input_indices():
                if ai < len(node.inputs):
                    child, _ = node.inputs[ai]
                    if child.is_variable:
                        aux_ids.add(id(child))
        args = [n for n in nodes if n.is_variable and id(n) not in aux_ids]
        auxs = [n for n in nodes if n.is_variable and id(n) in aux_ids]
        return args, auxs

    def list_arguments(self):
        return [n.name for n in self._arg_aux_split()[0]]

    def list_auxiliary_states(self):
        return [n.name for n in self._arg_aux_split()[1]]

    def list_inputs(self):
        return [n.name for n in self._nodes() if n.is_variable]

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.num_outputs() == 1:
                names.append(node.name + "_output" if not node.is_variable
                             else node.name)
            else:
                names.append(f"{node.name}_output{idx}")
        return names

    # -- composition sugar ---------------------------------------------------

    def __call__(self, *args, **kwargs):
        raise NotImplementedError("symbol re-composition via __call__ is not "
                                  "supported; build the graph with op calls")

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # arithmetic — lowered to the registered broadcast/scalar ops so the
    # symbolic and imperative paths share kernels
    def __add__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _binary(self, other, "broadcast_sub", "_rminus_scalar", swap=True)

    def __mul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _binary(self, other, "broadcast_div", "_rdiv_scalar", swap=True)

    def __pow__(self, other):
        return _binary(self, other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _binary(self, -1.0, None, "_mul_scalar")

    def __eq__(self, other):  # noqa: PLR0124 — symbolic eq builds a node
        return _binary(self, other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        return _binary(self, other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return _binary(self, other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return _binary(self, other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return _binary(self, other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _binary(self, other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def reshape(self, shape, **kwargs):
        from . import op as _op
        return _op.reshape(self, shape=shape, **kwargs)

    def astype(self, dtype):
        from . import op as _op
        return _op.cast(self, dtype=dtype)

    # -- serialization -------------------------------------------------------

    def tojson(self, remove_amp_cast=True):
        nodes = self._nodes()
        node_index = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        arg_nodes = []
        for i, n in enumerate(nodes):
            entry = {
                "op": "null" if n.is_variable else n.op,
                "name": n.name,
                "inputs": [[node_index[id(c)], oi, 0] for c, oi in n.inputs],
            }
            if n.attrs:
                entry["attrs"] = {k: _attr_to_str(v) for k, v in n.attrs.items()}
            if n.is_variable:
                arg_nodes.append(i)
            out_nodes.append(entry)
        heads = [[node_index[id(n)], oi, 0] for n, oi in self._outputs]
        graph = {
            "nodes": out_nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", _MXNET_VERSION]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname, remove_amp_cast=True):
        with open(fname, "w") as f:
            f.write(self.tojson(remove_amp_cast=remove_amp_cast))

    # -- shape/type inference ------------------------------------------------

    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes, unknown = self._infer_shape_impl(*args, **kwargs)
        if unknown:
            raise MXNetError(f"cannot fully infer shapes; unknown: {unknown}")
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes, _ = self._infer_shape_impl(*args, **kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def _infer_shape_impl(self, *args, **kwargs):
        import jax

        if args:
            names = self.list_arguments()
            for n, s in zip(names, args):
                if s is not None:
                    kwargs.setdefault(n, s)
        known = {k: tuple(v) for k, v in kwargs.items() if v is not None}
        dtypes = {}
        shapes = _infer_graph_shapes(self, known, dtypes)
        arg_nodes, aux_nodes = self._arg_aux_split()
        arg_shapes = [shapes.get((id(n), 0)) for n in arg_nodes]
        aux_shapes = [shapes.get((id(n), 0)) for n in aux_nodes]
        out_shapes = [shapes.get((id(n), oi)) for n, oi in self._outputs]
        unknown = [n.name for n, s in zip(arg_nodes, arg_shapes) if s is None]
        unknown += [n.name for n, s in zip(aux_nodes, aux_shapes) if s is None]
        return arg_shapes, out_shapes, aux_shapes, unknown

    def infer_type(self, *args, **kwargs):
        """Returns (arg_types, out_types, aux_types); defaults float32
        (the reference's type inference with default_dtype)."""
        names = self.list_arguments()
        given = dict(zip(names, args)) if args else dict(kwargs)
        arg_types = [_np.dtype(given.get(n, "float32")) for n in names]
        aux_types = [_np.dtype("float32")] * len(self.list_auxiliary_states())
        out_types = [_np.dtype(given.get(names[0], "float32")) if names
                     else _np.dtype("float32")] * len(self._outputs)
        return arg_types, out_types, aux_types

    # -- binding -------------------------------------------------------------

    def get_backend_symbol(self, backend):
        """Rewrite this symbol with a registered subgraph backend
        (reference symbol.py get_backend_symbol → MXGenBackendSubgraph)."""
        from .subgraph import build_subgraph

        return build_subgraph(self, backend)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor
        from .subgraph import apply_env_backend

        new_sym = apply_env_backend(self)
        if new_sym is not self:
            # a rewrite may move values between arg and aux roles (TPU_FUSE
            # turns BN moving stats into fused-op arguments): re-split the
            # caller's values against the NEW symbol's listings
            pool = {}
            if isinstance(args, dict):
                pool.update(args)
            elif isinstance(args, (list, tuple)):
                pool.update(zip(self.list_arguments(), args))
            if isinstance(aux_states, dict):
                pool.update(aux_states)
            elif isinstance(aux_states, (list, tuple)):
                pool.update(zip(self.list_auxiliary_states(), aux_states))
            if pool:
                args = {n: pool[n] for n in new_sym.list_arguments()
                        if n in pool}
                aux_states = {n: pool[n]
                              for n in new_sym.list_auxiliary_states()
                              if n in pool}
            if isinstance(args_grad, (list, tuple)):
                args_grad = dict(zip(self.list_arguments(), args_grad))
        return Executor(new_sym, ctx, args=args,
                        args_grad=args_grad, grad_req=grad_req,
                        aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Infer every argument shape from the given input shapes, allocate
        (zero-filled) arrays and bind (reference symbol.py:1376)."""
        from .executor import Executor
        from .subgraph import apply_env_backend
        from ..ndarray import zeros

        sym = apply_env_backend(self)
        arg_shapes, _, aux_shapes = sym.infer_shape(**kwargs)
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        type_dict = type_dict or {}
        args = {n: zeros(s, dtype=type_dict.get(n, "float32"))
                for n, s in zip(arg_names, arg_shapes)}
        auxs = {n: zeros(s, dtype=type_dict.get(n, "float32"))
                for n, s in zip(aux_names, aux_shapes)}
        args_grad = None
        if grad_req != "null":
            args_grad = {n: zeros(s) for n, s in zip(arg_names, arg_shapes)}
        return Executor(sym, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=auxs)

    # -- eval ----------------------------------------------------------------

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, args=kwargs)
        return ex.forward()

    def debug_str(self):
        lines = []
        for n in self._nodes():
            kind = "Variable" if n.is_variable else n.op
            ins = ", ".join(f"{c.name}[{oi}]" for c, oi in n.inputs)
            lines.append(f"{kind} {n.name} <- [{ins}]")
        return "\n".join(lines)


def _is_op_param(node, key):
    if node.is_variable:
        return False
    return True  # op attrs are op params unless double-underscored


def _attr_to_str(v):
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, (tuple, list)):
        if len(v) == 1:
            return f"({v[0]},)"  # "(64)" would literal_eval to a scalar
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _attr_from_str(s):
    if not isinstance(s, str):
        return s
    low = s.strip()
    if low in ("True", "true"):
        return True
    if low in ("False", "false"):
        return False
    try:
        return ast.literal_eval(low)
    except (ValueError, SyntaxError):
        return s


# -- construction -----------------------------------------------------------

def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (reference symbol.py var/Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attrs = dict(_attribute.current().get(attr) or {}) if hasattr(_attribute, "current") else dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype))
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    if stype is not None:
        attrs["__storage_type__"] = stype
    attrs.update({k: v for k, v in kwargs.items()})
    return Symbol([(_Node(None, name, attrs), 0)])


Variable = var


def Group(symbols):
    outs = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Expected a list of symbols")
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    graph = json.loads(json_str)
    raw_nodes = graph["nodes"]
    nodes = []
    for entry in raw_nodes:
        op = entry["op"]
        attrs_raw = entry.get("attrs", entry.get("param", {})) or {}
        attrs = {k: _attr_from_str(v) for k, v in attrs_raw.items()}
        node = _Node(None if op == "null" else op, entry["name"], attrs)
        node.inputs = [(nodes[i], oi) for i, oi, *_ in entry["inputs"]]
        nodes.append(node)
    heads = [(nodes[i], oi) for i, oi, *_ in graph["heads"]]
    return Symbol(heads)


# -- op application (called by the generated namespace) ----------------------

def _apply_op(op_name, *args, name=None, attr=None, **kwargs):
    """Create a graph node for `op_name`, auto-creating missing parameter
    variables the MXNet way (`fc1` → `fc1_weight`, `fc1_bias`)."""
    op = _reg.get_op(op_name)
    required, optional, open_varargs, aux_idx = _op_input_spec(op)

    hint = op_name.lstrip("_").lower()
    name = _name_mod.current().get(name, hint)

    sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
    attrs = {k: v for k, v in kwargs.items()
             if not isinstance(v, Symbol) and v is not None}
    # unknown kwargs are errors, not silent no-ops (dmlc::Parameter Init
    # role) — checked BEFORE merging attr=, which carries arbitrary node
    # metadata (ctx_group, __lr_mult__, AttrScope) by contract
    _reg.validate_attrs(op, attrs)
    if attr:
        attrs.update(attr)

    pos_syms = [a for a in args if isinstance(a, Symbol)]
    # None positionals keep their slot only for declared optional tensor
    # inputs (op.tensor_opts, e.g. CTCLoss lengths); elsewhere they are
    # skipped (gluon passes bias=None for no_bias layers).  Other
    # non-symbol positionals are rejected.
    extra_pos = [a for a in args if not isinstance(a, Symbol) and a is not None]
    if extra_pos:
        raise MXNetError(f"{op_name}: positional non-symbol args not "
                         f"supported in symbol API; pass as keywords")

    inputs = []
    if open_varargs:
        inputs = [(s._outputs[0][0], s._outputs[0][1]) for s in pos_syms]
        for k, v in sym_kwargs.items():
            inputs.append((v._outputs[0][0], v._outputs[0][1]))
    else:
        pos_iter = iter(pos_syms)
        n_pos_used = 0
        no_bias = bool(attrs.get("no_bias", False))
        for in_name in required:
            s = sym_kwargs.pop(in_name, None)
            if s is None:
                s = next(pos_iter, None)
                if s is not None:
                    n_pos_used += 1
            if s is None:
                s = var(f"{name}_{in_name}")
            if len(s._outputs) != 1:
                raise MXNetError(f"{op_name} input {in_name}: grouped symbol "
                                 f"cannot be an op input")
            inputs.append(s._outputs[0])
        if optional is not None and not no_bias:
            s = sym_kwargs.pop(optional, None)
            if s is None:
                s = next(pos_iter, None)
                if s is not None:
                    n_pos_used += 1
            if s is None:
                s = var(f"{name}_{optional}")
            inputs.append(s._outputs[0])
        if op.tensor_opts:
            # map the raw positional tail (None placeholders preserved)
            # onto the declared optional tensor slots, in order
            raw_tail, seen = [], 0
            for a in args:
                if isinstance(a, Symbol):
                    seen += 1
                    if seen > n_pos_used:
                        raw_tail.append(a)
                elif a is None:
                    raw_tail.append(None)
            if len(raw_tail) > len(op.tensor_opts):
                raise MXNetError(f"{op_name}: too many symbol inputs")
            bound_opts = []
            for slot, a in zip(op.tensor_opts, raw_tail):
                s = sym_kwargs.pop(slot, None)
                if s is None and isinstance(a, Symbol):
                    s = a
                if s is not None:
                    inputs.append(s._outputs[0])
                    bound_opts.append(slot)
            for slot in op.tensor_opts[len(raw_tail):]:
                s = sym_kwargs.pop(slot, None)
                if s is not None:
                    inputs.append(s._outputs[0])
                    bound_opts.append(slot)
            if bound_opts:
                attrs["__opt_in__"] = ",".join(bound_opts)
            leftover = []
        else:
            leftover = list(pos_iter)
        if leftover or sym_kwargs:
            raise MXNetError(f"{op_name}: too many symbol inputs "
                             f"(leftover={len(leftover)}, kw={list(sym_kwargs)})")

    node = _Node(op_name, name, attrs, inputs)
    n_out = node.num_outputs()
    sym = Symbol([(node, i) for i in range(n_out)])
    # multi-output stateful ops (BatchNorm) expose only the primary output
    # for composition; extra outputs are the aux write-backs
    if aux_idx and n_out > 1:
        return Symbol([(node, 0)])
    return sym


# -- shape inference over the graph ------------------------------------------

def _infer_graph_shapes(sym, known, dtypes):
    """Forward shape propagation with per-op parameter back-fill rules.

    Walks topo order; a node whose data-input shape is known back-fills its
    parameter variables' shapes via `_PARAM_SHAPE_RULES` (the role of the
    reference's bidirectional FInferShape, `infer_graph_attr_pass.cc:94` —
    full bidirectional fixpoint isn't needed for the practical graphs the
    Module API sees).
    """
    import jax
    import jax.numpy as jnp

    shapes: dict = {}

    def set_var(node, shape):
        shapes[(id(node), 0)] = tuple(int(x) for x in shape)

    nodes = _topo_order([n for n, _ in sym._outputs])
    for node in nodes:
        if node.is_variable:
            if node.name in known:
                set_var(node, known[node.name])
            elif "__shape__" in node.attrs:
                set_var(node, node.attrs["__shape__"])

    progress = True
    while progress:
        progress = False
        for node in nodes:
            if node.is_variable:
                continue
            if all((id(c), oi) in shapes for c, oi in node.inputs):
                if (id(node), 0) in shapes:
                    continue
                in_shapes = [shapes[(id(c), oi)] for c, oi in node.inputs]
                out_sh = _eval_node_shapes(node, in_shapes)
                for i, s in enumerate(out_sh):
                    shapes[(id(node), i)] = s
                progress = True
            else:
                rule = _PARAM_SHAPE_RULES.get(node.op)
                if rule is None:
                    continue
                filled = rule(node, shapes)
                if filled:
                    progress = True
    return shapes


def _eval_node_shapes(node, in_shapes):
    import jax
    import jax.numpy as jnp

    attrs = dict(node.attrs)
    attrs.pop("__shape__", None)
    op = _reg.get_op(node.op)
    if op.needs_mode:
        attrs.setdefault("_train", False)
    fn = _reg.bound_fn(node.op, **{k: v for k, v in attrs.items()
                                   if not k.startswith("__")})
    specs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]
    opt_in = node.attrs.get("__opt_in__") or ""
    kw_specs = {}
    if opt_in:
        names = opt_in.split(",")
        n_pos = len(specs) - len(names)
        kw_specs = dict(zip(names, specs[n_pos:]))
        specs = specs[:n_pos]
    if op.needs_rng:
        key_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)
        out = jax.eval_shape(fn, key_spec, *specs, **kw_specs)
    else:
        out = jax.eval_shape(fn, *specs, **kw_specs)
    if isinstance(out, (list, tuple)):
        return [tuple(o.shape) for o in out]
    return [tuple(out.shape)]


def _rule(required_idx_shapes):
    """Helper producing a back-fill rule from {input_index: shape_fn}."""

    def apply(node, shapes):
        data = node.inputs[0]
        if (id(data[0]), data[1]) not in shapes:
            return False
        data_shape = shapes[(id(data[0]), data[1])]
        filled = False
        for idx, shape_fn in required_idx_shapes(node, data_shape).items():
            if idx >= len(node.inputs):
                continue
            child, oi = node.inputs[idx]
            if child.is_variable and (id(child), oi) not in shapes:
                shapes[(id(child), oi)] = tuple(int(x) for x in shape_fn)
                filled = True
        return filled

    return apply


def _fc_rule(node, dsh):
    nh = int(node.attrs.get("num_hidden"))
    flatten = node.attrs.get("flatten", True)
    in_dim = int(_np.prod(dsh[1:])) if flatten in (True, "True", 1) else int(dsh[-1])
    return {1: (nh, in_dim), 2: (nh,)}


def _conv_rule(node, dsh):
    kernel = _as_shape(node.attrs.get("kernel"))
    nf = int(node.attrs.get("num_filter"))
    ng = int(node.attrs.get("num_group", 1))
    return {1: (nf, dsh[1] // ng) + kernel, 2: (nf,)}


def _deconv_rule(node, dsh):
    kernel = _as_shape(node.attrs.get("kernel"))
    nf = int(node.attrs.get("num_filter"))
    ng = int(node.attrs.get("num_group", 1))
    return {1: (dsh[1], nf // ng) + kernel, 2: (nf,)}


def _bn_rule(node, dsh):
    axis = int(node.attrs.get("axis", 1))
    c = dsh[axis % len(dsh)]
    return {1: (c,), 2: (c,), 3: (c,), 4: (c,)}


def _ln_rule(node, dsh):
    axis = int(node.attrs.get("axis", -1))
    c = dsh[axis % len(dsh)]
    return {1: (c,), 2: (c,)}


def _in_rule(node, dsh):
    return {1: (dsh[1],), 2: (dsh[1],)}


def _embed_rule(node, dsh):
    return {1: (int(node.attrs["input_dim"]), int(node.attrs["output_dim"]))}


def _prelu_rule(node, dsh):
    if node.attrs.get("act_type", "leaky") in ("prelu",):
        return {1: (dsh[1] if len(dsh) > 1 else 1,)}
    return {}


def _through_quantize(entry):
    """See through a _contrib_quantize_v2 node to its float input entry
    (shape-preserving), so param back-fill reaches the weight variable."""
    child, oi = entry
    if not child.is_variable and child.op == "_contrib_quantize_v2" and oi == 0:
        return child.inputs[0]
    return entry


def _quantized_rule(shape_fn):
    """Back-fill rule for quantized conv/FC: data/weight arrive through
    quantize_v2 nodes; the rule resolves both through them."""

    def apply(node, shapes):
        d_child, d_oi = _through_quantize(node.inputs[0])
        key = (id(d_child), d_oi)
        if key not in shapes:
            return False
        dsh = shapes[key]
        filled = False
        for idx, shape in shape_fn(node, dsh).items():
            child, oi = _through_quantize(node.inputs[idx])
            if child.is_variable and (id(child), oi) not in shapes:
                shapes[(id(child), oi)] = tuple(int(x) for x in shape)
                filled = True
        return filled

    return apply


def _quantized_conv_shapes(node, dsh):
    return {1: _conv_rule(node, dsh)[1]}  # one weight-shape formula only


def _quantized_fc_shapes(node, dsh):
    return {1: _fc_rule(node, dsh)[1]}


def _fused_conv_rule(node, dsh):
    per = {1: _conv_rule(node, dsh)[1]}
    nf = int(node.attrs.get("num_filter"))
    for i in range(2, 7):  # bias, gamma, beta, moving_mean, moving_var
        per[i] = (nf,)
    return per


def _as_shape(v):
    if v is None:
        return ()
    if isinstance(v, (int, float)):
        return (int(v),)
    return tuple(int(x) for x in v)


_PARAM_SHAPE_RULES = {
    "FullyConnected": _rule(_fc_rule),
    "Convolution": _rule(_conv_rule),
    "Deconvolution": _rule(_deconv_rule),
    "BatchNorm": _rule(_bn_rule),
    "BatchNorm_v1": _rule(_bn_rule),
    "_contrib_SyncBatchNorm": _rule(_bn_rule),
    "LayerNorm": _rule(_ln_rule),
    "InstanceNorm": _rule(_in_rule),
    "Embedding": _rule(_embed_rule),
    "LeakyReLU": _rule(_prelu_rule),
    "_fused_conv_bn_relu": _rule(_fused_conv_rule),
    "_contrib_quantized_conv": _quantized_rule(_quantized_conv_shapes),
    "_contrib_quantized_fully_connected": _quantized_rule(_quantized_fc_shapes),
}


def _binary(lhs, rhs, broadcast_op, scalar_op, swap=False):
    from . import op as _op

    if isinstance(rhs, Symbol):
        if broadcast_op is None:
            raise MXNetError("unsupported symbol-symbol operation")
        return _apply_op(broadcast_op, lhs, rhs)
    if isinstance(rhs, (int, float, bool, _np.number)):
        return _apply_op(scalar_op, lhs, scalar=float(rhs))
    raise TypeError(f"unsupported operand type {type(rhs)}")
