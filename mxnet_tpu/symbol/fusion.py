"""Conv+BN(+ReLU) fusion — the demo SubgraphProperty.

Parity role: the MKLDNN conv fusion backend
(`src/operator/subgraph/mkldnn/mkldnn_conv.cc` + its
`MXNET_REGISTER_SUBGRAPH_PROPERTY(MKLDNN, ...)`): Convolution → BatchNorm
(→ relu) chains collapse into one `_fused_conv_bn_relu` node with the BN
folded into the convolution parameters at run time. Inference-only (the
fused op consumes the moving statistics), like the reference's deployment
fusions; registered as backend ``TPU_FUSE``:

    fused = sym.get_backend_symbol("TPU_FUSE")
"""
from __future__ import annotations

from .subgraph import (SubgraphProperty, SubgraphSelector,
                       register_subgraph_property)


def _is_relu(node):
    """Either spelling of ReLU: the `Activation(act_type='relu')` op or
    the standalone `relu` op (gluon emits the former, hand-built symbols
    and imported graphs often the latter)."""
    if node.op == "relu":
        return True
    return node.op == "Activation" and \
        str(node.attrs.get("act_type", "")) == "relu"


class _ConvBNReLUSelector(SubgraphSelector):
    def select(self, node):
        return node.op == "Convolution"

    def select_output(self, node, output_node):
        if node.op == "Convolution" and output_node.op == "BatchNorm":
            # BN must consume THIS conv's main output
            return bool(output_node.inputs) and output_node.inputs[0][0] is node
        if node.op == "BatchNorm" and _is_relu(output_node):
            return bool(output_node.inputs) and output_node.inputs[0][0] is node
        return False


class ConvBNReLUProperty(SubgraphProperty):
    def create_subgraph_selector(self):
        return _ConvBNReLUSelector()

    def create_subgraph_node(self, subgraph_sym, input_entries, subgraph_id):
        from .symbol import _apply_op

        nodes = subgraph_sym._nodes()
        conv = next((n for n in nodes if n.op == "Convolution"), None)
        bn = next((n for n in nodes if n.op == "BatchNorm"), None)
        act = next((n for n in nodes if n.op and _is_relu(n)), None)
        if conv is None or bn is None or len(subgraph_sym._outputs) != 1:
            return None  # not the exact shape this fusion handles
        names = (subgraph_sym.list_arguments()
                 + subgraph_sym.list_auxiliary_states())
        entry = dict(zip(names, input_entries))

        def of(node, i):
            child, _ = node.inputs[i]
            return entry.get(child.name)

        data = of(conv, 0)
        weight = of(conv, 1)
        bias = of(conv, 2) if len(conv.inputs) > 2 else None
        gamma, beta = of(bn, 1), of(bn, 2)
        mean, variance = of(bn, 3), of(bn, 4)
        if any(x is None for x in (data, weight, gamma, beta, mean, variance)):
            return None  # a role is fed by an inner node — bail out
        if bias is None:
            bias = _apply_op("_zeros",
                             shape=(int(conv.attrs.get("num_filter", 0)),),
                             dtype="float32")
        from ..lazy.rewrite import fused_conv_bn_attrs

        attrs = fused_conv_bn_attrs(conv.attrs, bn.attrs, act is not None)
        return _apply_op(
            "_fused_conv_bn_relu", data, weight, bias, gamma, beta, mean,
            variance, name=f"fused_conv{subgraph_id}", **attrs)


register_subgraph_property("TPU_FUSE", ConvBNReLUProperty)
