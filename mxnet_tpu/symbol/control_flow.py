"""Symbolic control-flow frontends — symbol.contrib.foreach/while_loop/cond.

Parity: `python/mxnet/symbol/contrib.py` (foreach/while_loop/cond cut NNVM
subgraphs and deduce free-variable inputs).  Here the body callables build a
Symbol sub-DAG over placeholder variables; free variables (weights etc. the
body closes over) are discovered as the sub-DAG's non-placeholder variable
leaves and wired as extra node inputs, so binding and autograd treat them
like any other input.  The subgraph travels as a JSON attribute (survives
save/load); execution lowers to lax.scan/lax.cond in
`ops/control_flow_ops.py`.
"""
from __future__ import annotations

import itertools

from .symbol import Symbol, var, Group, _Node, _topo_order

__all__ = ["foreach", "while_loop", "cond"]

_uid = itertools.count()


def _flatten(x):
    if isinstance(x, Symbol):
        return [x], None
    if x is None:
        return [], ()
    flat, struct = [], []
    for item in x:
        f, s = _flatten(item)
        flat.extend(f)
        struct.append((s, len(f)))
    return flat, struct


def _unflatten(flat, struct):
    if struct is None:
        return flat[0]
    out, i = [], 0
    for s, n in struct:
        out.append(_unflatten(flat[i:i + n], s))
        i += n
    return out


def _free_vars(heads, placeholder_names):
    """Non-placeholder variable leaves of the sub-DAG, topo order."""
    frees = []
    for node in _topo_order([n for n, _ in heads._outputs]):
        if node.is_variable and node.name not in placeholder_names:
            frees.append(node)
    return frees


def _outputs_of(node, n):
    return [Symbol([(node, i)]) for i in range(n)]


def foreach(body, data, init_states, name=None):
    """Symbolic foreach (reference `_foreach`, control_flow.cc:1255)."""
    name = name or f"foreach{next(_uid)}"
    data_l, data_struct = _flatten(data)
    states_l, states_struct = _flatten(init_states)
    if not data_l:
        raise ValueError("foreach: data must contain at least one symbol")

    slice_vars = [var(f"{name}_slice{i}") for i in range(len(data_l))]
    state_vars = [var(f"{name}_state{i}") for i in range(len(states_l))]
    out, new_s = body(_unflatten(slice_vars, data_struct),
                      _unflatten(state_vars, states_struct))
    out_l, out_struct = _flatten(out)
    ns_l, ns_struct = _flatten(new_s)
    if len(ns_l) != len(states_l):
        raise ValueError(f"foreach: body returned {len(ns_l)} states, "
                         f"expected {len(states_l)}")
    sub = Group(out_l + ns_l)

    ph = {s._outputs[0][0].name for s in slice_vars + state_vars}
    frees = _free_vars(sub, ph)
    sub_args = [s._outputs[0][0].name for s in slice_vars + state_vars] + \
               [f.name for f in frees]

    inputs = [s._outputs[0] for s in data_l + states_l] + \
             [(f, 0) for f in frees]
    attrs = {
        "subgraph": sub.tojson(), "sub_args": ",".join(sub_args),
        "n_data": len(data_l), "n_states": len(states_l),
        "n_out": len(out_l), "__opt_in__": "",
    }
    node = _Node("_foreach", name, attrs, inputs)
    outs = _outputs_of(node, len(out_l) + len(ns_l))
    outputs = _unflatten(outs[:len(out_l)], out_struct) if out_l else []
    states = _unflatten(outs[len(out_l):], ns_struct) if ns_l else []
    return outputs, states


def while_loop(cond, func, loop_vars, max_iterations=None, name=None):
    """Symbolic while_loop (reference `_while_loop`, control_flow.cc:1316).
    Bounded: requires `max_iterations` (static trip count for XLA); step
    outputs are stacked to (max_iterations, ...) with zero padding."""
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations")
    name = name or f"while{next(_uid)}"
    lv_l, lv_struct = _flatten(loop_vars)
    if not lv_l:
        raise ValueError("while_loop: loop_vars must be non-empty")

    lv_vars = [var(f"{name}_lv{i}") for i in range(len(lv_l))]
    lv_args = _unflatten(lv_vars, lv_struct)
    lv_list = lv_args if isinstance(lv_args, list) else [lv_args]

    c_sym = cond(*lv_list)
    if not isinstance(c_sym, Symbol):
        raise TypeError("while_loop: cond must return a Symbol")
    out, new_lv = func(*lv_list)
    out_l, out_struct = _flatten(out)
    nl_l, _ = _flatten(new_lv)
    if len(nl_l) != len(lv_l):
        raise ValueError(f"while_loop: func returned {len(nl_l)} loop_vars, "
                         f"expected {len(lv_l)}")
    body_sub = Group(out_l + nl_l)

    lv_names = [v._outputs[0][0].name for v in lv_vars]
    ph = set(lv_names)
    c_frees = _free_vars(c_sym, ph)
    b_frees = _free_vars(body_sub, ph)

    def _used_names(sym_like, placeholders):
        return [n.name for n in _topo_order([x for x, _ in sym_like._outputs])
                if n.is_variable]

    cond_args = _used_names(c_sym, ph)
    body_args = _used_names(body_sub, ph)
    free_nodes, seen = [], set(lv_names)
    for f in c_frees + b_frees:
        if f.name not in seen:
            seen.add(f.name)
            free_nodes.append(f)

    inputs = [s._outputs[0] for s in lv_l] + [(f, 0) for f in free_nodes]
    attrs = {
        "cond_subgraph": c_sym.tojson(), "body_subgraph": body_sub.tojson(),
        "cond_args": ",".join(cond_args), "body_args": ",".join(body_args),
        "lv_names": ",".join(lv_names),
        "n_lv": len(lv_l), "n_out": len(out_l),
        "max_iterations": int(max_iterations),
    }
    node = _Node("_while_loop", name, attrs, inputs)
    outs = _outputs_of(node, len(out_l) + len(lv_l))
    outputs = _unflatten(outs[:len(out_l)], out_struct) if out_l else []
    final_lv = _unflatten(outs[len(out_l):], lv_struct)
    return outputs, final_lv


def cond(pred, then_func, else_func, name=None):
    """Symbolic cond (reference `_cond`, control_flow.cc:1378)."""
    name = name or f"cond{next(_uid)}"
    if not isinstance(pred, Symbol):
        raise TypeError("cond: pred must be a Symbol")
    t_out = then_func()
    e_out = else_func()
    t_l, t_struct = _flatten(t_out)
    e_l, _ = _flatten(e_out)
    if len(t_l) != len(e_l):
        raise ValueError("cond: then/else must return the same number of "
                         "outputs")
    t_sub, e_sub = Group(t_l), Group(e_l)

    t_args = [n.name for n in _topo_order([x for x, _ in t_sub._outputs])
              if n.is_variable]
    e_args = [n.name for n in _topo_order([x for x, _ in e_sub._outputs])
              if n.is_variable]
    free_nodes, seen = [], set()
    for f in _free_vars(t_sub, set()) + _free_vars(e_sub, set()):
        if f.name not in seen:
            seen.add(f.name)
            free_nodes.append(f)

    inputs = [pred._outputs[0]] + [(f, 0) for f in free_nodes]
    attrs = {
        "then_subgraph": t_sub.tojson(), "else_subgraph": e_sub.tojson(),
        "then_args": ",".join(t_args), "else_args": ",".join(e_args),
        "n_out": len(t_l),
    }
    node = _Node("_cond", name, attrs, inputs)
    outs = _outputs_of(node, len(t_l))
    return _unflatten(outs, t_struct)
