"""ctypes bindings for the native host runtime (librt_tpu.so).

Three components, mirroring the host-side slice of the reference's C++
core (SURVEY.md §2.1):

* :class:`NativeEngine` — the dependency engine (`src/engine.cc`;
  reference `src/engine/threaded_engine.cc`): python callables pushed with
  const/mutable variable lists run on native worker threads with reads
  concurrent and writes exclusive+ordered per variable.
* :class:`NativeRecordIO` — mmap'd RecordIO frame scanner
  (`src/recordio.cc`; reference dmlc-core recordio / `src/io/`).
* :class:`SharedMemoryArena` — named POSIX shm segments
  (`src/arena.cc`; reference `cpu_shared_storage_manager.h`).
"""
from __future__ import annotations

import ctypes
import itertools
import threading

import numpy as np

_CALLBACK = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _bind(lib):
    lib.rt_engine_create.restype = ctypes.c_void_p
    lib.rt_engine_create.argtypes = [ctypes.c_int]
    lib.rt_engine_destroy.argtypes = [ctypes.c_void_p]
    lib.rt_engine_new_var.restype = ctypes.c_void_p
    lib.rt_engine_new_var.argtypes = [ctypes.c_void_p]
    lib.rt_engine_push.argtypes = [
        ctypes.c_void_p, _CALLBACK, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
    lib.rt_engine_wait_all.argtypes = [ctypes.c_void_p]

    lib.rt_recordio_open.restype = ctypes.c_void_p
    lib.rt_recordio_open.argtypes = [ctypes.c_char_p]
    lib.rt_recordio_close.argtypes = [ctypes.c_void_p]
    lib.rt_recordio_size.restype = ctypes.c_uint64
    lib.rt_recordio_size.argtypes = [ctypes.c_void_p]
    lib.rt_recordio_count.restype = ctypes.c_int64
    lib.rt_recordio_count.argtypes = [ctypes.c_void_p]
    lib.rt_recordio_scan.restype = ctypes.c_int64
    lib.rt_recordio_scan.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int64]
    lib.rt_recordio_data.restype = ctypes.c_void_p
    lib.rt_recordio_data.argtypes = [ctypes.c_void_p]

    lib.rt_shm_create.restype = ctypes.c_void_p
    lib.rt_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rt_shm_attach.restype = ctypes.c_void_p
    lib.rt_shm_attach.argtypes = [ctypes.c_char_p]
    lib.rt_shm_ptr.restype = ctypes.c_void_p
    lib.rt_shm_ptr.argtypes = [ctypes.c_void_p]
    lib.rt_shm_size.restype = ctypes.c_uint64
    lib.rt_shm_size.argtypes = [ctypes.c_void_p]
    lib.rt_shm_detach.argtypes = [ctypes.c_void_p]
    lib.rt_shm_unlink.restype = ctypes.c_int
    lib.rt_shm_unlink.argtypes = [ctypes.c_char_p]
    return lib


class NativeEngine:
    """Host dependency engine (reference Engine::PushAsync semantics).

    ONE shared CFUNCTYPE trampoline serves every op — the per-op python
    payload travels as the integer id in the callback's void* argument.
    A per-op closure would have to be freed eventually, and freeing a
    libffi closure that a native thread is still returning through is a
    use-after-free; the shared trampoline lives as long as the engine."""

    def __init__(self, lib, num_threads=4):
        self._lib = _bind(lib)
        self._handle = self._lib.rt_engine_create(int(num_threads))
        self._pending = {}  # op id -> (fn, args, kwargs)
        self._ids = itertools.count(1)
        self._mu = threading.Lock()

        def trampoline(payload):
            op_id = int(payload or 0)
            with self._mu:
                entry = self._pending.pop(op_id, None)
            if entry is not None:
                f, a, kw = entry
                f(*a, **kw)

        self._trampoline = _CALLBACK(trampoline)  # kept alive with the engine

    def new_var(self):
        """A fresh scheduling variable (engine.h NewVariable)."""
        return self._lib.rt_engine_new_var(self._handle)

    def push(self, fn, args=(), kwargs=None, const_vars=(), mutable_vars=()):
        """Run ``fn(*args, **kwargs)`` on an engine thread once every
        listed variable dependency clears."""
        op_id = next(self._ids)
        with self._mu:
            self._pending[op_id] = (fn, args, kwargs or {})
        carr = (ctypes.c_void_p * max(1, len(const_vars)))(*const_vars)
        marr = (ctypes.c_void_p * max(1, len(mutable_vars)))(*mutable_vars)
        self._lib.rt_engine_push(self._handle, self._trampoline,
                                 ctypes.c_void_p(op_id),
                                 carr, len(const_vars), marr, len(mutable_vars))
        return op_id

    def wait_all(self):
        self._lib.rt_engine_wait_all(self._handle)


class NativeRecordIO:
    """mmap'd frame index over a RecordIO file; O(file) native scan, then
    zero-copy `memoryview` reads per record."""

    def __init__(self, lib, path):
        self._lib = _bind(lib)
        self._handle = self._lib.rt_recordio_open(path.encode())
        if not self._handle:
            raise IOError(f"cannot open recordio file {path}")
        n = self._lib.rt_recordio_count(self._handle)
        if n < 0:
            self.close()
            raise IOError(f"corrupt recordio framing in {path}")
        offsets = (ctypes.c_uint64 * n)()
        lengths = (ctypes.c_uint64 * n)()
        cflags = (ctypes.c_uint32 * n)()
        got = self._lib.rt_recordio_scan(self._handle, offsets, lengths,
                                         cflags, n)
        assert got == n
        self.offsets = np.ctypeslib.as_array(offsets).copy()
        self.lengths = np.ctypeslib.as_array(lengths).copy()
        self.cflags = np.ctypeslib.as_array(cflags).copy()
        size = self._lib.rt_recordio_size(self._handle)
        base = self._lib.rt_recordio_data(self._handle)
        self._buf = (ctypes.c_char * size).from_address(base)

    def __len__(self):
        return len(self.offsets)

    def read_frame(self, i):
        """Raw payload bytes of frame i (no split reassembly)."""
        off, ln = int(self.offsets[i]), int(self.lengths[i])
        return bytes(memoryview(self._buf)[off:off + ln])

    def read_records(self):
        """All LOGICAL records, reassembling split frames (dmlc-core
        convention, same as `MXRecordIO.read`: cflag 0=whole, 1=first,
        2=middle, 3=last)."""
        out = []
        parts = None
        for i in range(len(self)):
            c = int(self.cflags[i])
            if c == 0:
                out.append(self.read_frame(i))
            elif c == 1:
                parts = [self.read_frame(i)]
            elif c == 2:
                parts.append(self.read_frame(i))
            elif c == 3:
                parts.append(self.read_frame(i))
                out.append(b"".join(parts))
                parts = None
        return out

    def close(self):
        if self._handle:
            self._buf = None
            self._lib.rt_recordio_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class SharedMemoryArena:
    """Named POSIX shm segment usable as a numpy buffer across processes."""

    def __init__(self, lib, name, size=None, create=False):
        self._lib = _bind(lib)
        self.name = name
        if create:
            self._handle = self._lib.rt_shm_create(name.encode(), int(size))
        else:
            self._handle = self._lib.rt_shm_attach(name.encode())
        if not self._handle:
            raise OSError(f"shm {'create' if create else 'attach'} failed: {name}")
        self.size = self._lib.rt_shm_size(self._handle)
        ptr = self._lib.rt_shm_ptr(self._handle)
        self._buf = (ctypes.c_char * self.size).from_address(ptr)

    def asarray(self, dtype=np.uint8, shape=None):
        arr = np.frombuffer(self._buf, dtype=dtype)
        return arr.reshape(shape) if shape is not None else arr

    def detach(self):
        if self._handle:
            self._buf = None
            self._lib.rt_shm_detach(self._handle)
            self._handle = None

    def unlink(self):
        self._lib.rt_shm_unlink(self.name.encode())

    def __del__(self):
        try:
            self.detach()
        except Exception:
            pass


class NativeImagePipe:
    """Batch JPEG decode+augment workers (`src/imgpipe.cc`; reference
    `iter_image_recordio_2.cc:873` decode threads): one GIL-free C call
    decodes a whole batch to CHW float32 with shorter-side resize,
    random/center crop, mirror and mean/std normalize."""

    def __init__(self, lib, num_threads=4):
        self._lib = lib
        fn = getattr(lib, "rt_imgpipe_decode_batch", None)
        if fn is None:
            raise OSError("librt_tpu.so built without libjpeg support")
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint8)]
        self._fn = fn
        self._nthreads = max(1, int(num_threads))

    def decode_batch(self, buffers, out_h, out_w, resize_short=0,
                     rand_crop=False, rand_mirror=False, seed=0,
                     mean=None, std=None, nthreads=None):
        """Decode a list of JPEG byte buffers -> ((n, 3, out_h, out_w)
        float32, failed_indices). Images whose native decode failed
        (corrupt/exotic JPEG) are listed in failed_indices and their out
        rows are undefined — the caller re-decodes ONLY those in python.
        Returns (None, None) on argument-level failure."""
        n = len(buffers)
        bufs = (ctypes.c_char_p * n)(*buffers)
        lens = (ctypes.c_uint64 * n)(*[len(b) for b in buffers])
        out = np.empty((n, 3, out_h, out_w), np.float32)
        status = np.zeros((n,), np.uint8)

        def f3(v):
            if v is None:
                return None
            # scalars broadcast across channels, like ColorNormalizeAug
            vals = np.broadcast_to(np.ravel(np.asarray(v, np.float64)), (3,))
            return (ctypes.c_float * 3)(*[float(x) for x in vals])

        m, s = f3(mean), f3(std)
        rc = self._fn(
            n, ctypes.cast(bufs, ctypes.POINTER(ctypes.c_char_p)), lens,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(out_h), int(out_w), int(resize_short), int(bool(rand_crop)),
            int(bool(rand_mirror)), int(seed) & 0xFFFFFFFFFFFFFFFF,
            m, s, int(nthreads or self._nthreads),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc < 0:
            return None, None
        failed = np.nonzero(status == 0)[0].tolist()
        return out, failed
