"""CompileCache — the explicit, observable jit-executable cache.

The reference amortizes graph setup through CachedOp's signature-keyed
graph cache (`src/imperative/cached_op.cc` `SetForwardGraph`:295 — shape/
dtype of every input is the key). Here the executables are `jax.jit`
callables, and before this module they were held in anonymous
`functools.lru_cache`s: a bucketing run or a partial last batch that
churned shapes recompiled *silently*, which is exactly the failure mode
BENCH_r05 could not attribute. Every compiled-callable cache in the
framework (symbol executors, CachedOp, the fused train step, the fused
optimizer update) now lives in a named :class:`CompileCache`, so the
registry answers the three questions a perf round asks:

* how many distinct programs exist (``compile.cache_entries`` gauge),
* how often a step re-used one (``compile.cache_hits`` /
  ``compile.cache_misses`` counters),
* how long the misses cost (``compile.seconds`` counter — the first
  invocation of a cached callable is timed: jax traces + XLA-compiles
  synchronously on first call, so first-call time ≈ compile time).

Counters are recorded unconditionally (one lock-protected increment per
step — noise next to a dispatch) so cache accounting works even when the
wider telemetry plane is off.

Persistent on-disk XLA cache: ``MXNET_COMPILE_CACHE_DIR=<dir>`` points
jax's compilation cache at ``<dir>`` so a program compiled once (e.g. in a
warm-up window) is deserialized, not re-built, by every later process —
the `tools/compile_ladder.py` / bench `.jax_cache` mechanism promoted to a
first-class framework knob.
"""
from __future__ import annotations

import contextlib
import threading
import time
import warnings
import weakref

from . import telemetry
from .base import getenv, register_env

__all__ = ["CompileCache", "persistent_cache_dir", "stats", "named_stats",
           "name_totals", "all_caches", "donation_warnings_suppressed",
           "trace_salt", "dump_audit", "audit_ledger"]

register_env("MXNET_FUSED_STEP", True,
             "fuse forward+backward+optimizer update into one jitted XLA "
             "computation per step (0 falls back to the eager per-op path)")
register_env("MXNET_COMPILE_CACHE_DIR", "",
             "directory for jax's persistent on-disk XLA compilation cache "
             "(compile once per program across processes)")
register_env("MXNET_HLOLINT_DUMP", "",
             "directory for compiled-program audit dumps: at process exit "
             "every audited cache entry's program summary (collective "
             "inventory, donation aliasing, residency) is written as JSON "
             "for the tools/hlolint contract gate")
register_env("MXNET_HLOLINT_CACHES", "spmd,zero1,pipeline,serving,"
             "generation,lazy",
             "comma-separated audit tags recorded for the hlolint dump "
             "(a cache entry's tag is its get_or_build audit= label, "
             "defaulting to the cache name)")
register_env("MXNET_HLOLINT_MAX_ENTRIES", 16,
             "per-tag cap on audited entries in one process (each dump "
             "entry re-lowers — and for donated programs recompiles — "
             "the executable at exit)")

_caches = weakref.WeakSet()
_caches_lock = threading.Lock()

# hlolint audit ledger (MXNET_HLOLINT_DUMP): strong refs to the first
# MXNET_HLOLINT_MAX_ENTRIES executables per audit tag, recorded at first
# call so the exit hook can AOT-lower them after the suites that warmed
# them have let their per-context caches die. Empty (and never appended
# to) when the env var is unset — steady state pays one getenv per MISS.
_audit_lock = threading.Lock()
_audit_ledger = {}   # (tag, repr(key)) -> {cache, tag, key, fn, avals}
_audit_hooked = [False]

# monotonic per-NAME hit/miss/compile-time totals, surviving cache GC —
# `named_stats("serving")` must answer "did steady state compile anything?"
# with a counter that can only grow, not a sum over whatever instances
# happen to still be alive (a collected Predictor would silently subtract
# its history and break delta-based zero-compile assertions)
_name_totals = {}


def _totals(name):
    with _caches_lock:
        t = _name_totals.get(name)
        if t is None:
            t = _name_totals[name] = {"hits": 0, "misses": 0,
                                      "compile_seconds": 0.0}
        return t

# Process-unique constant mixed into donated programs' HLO (trace_salt):
# a donated-buffer executable deserialized from the on-disk cache by a
# LATER process has broken input-output aliasing on XLA:CPU and corrupts
# the heap when invoked ('corrupted double-linked list' — reproduced).
# Salting makes such a program's cache key unique to this process, so no
# other process can ever deserialize it, independent of jax-version
# differences in how the persistent cache can be gated.
import os as _os
import time as _time

_PROCESS_SALT = float(_os.getpid() * 4096 + (_time.time_ns() % 4096))


def trace_salt(x):
    """Mix the process-unique constant into a traced value without changing
    it (``x + zeros_like(x) * salt`` — exact for any finite salt). Donated
    programs call this on one traced argument so their HLO, and thus their
    persistent-cache key, is unique to this process."""
    import jax.numpy as jnp

    return x + jnp.zeros_like(x) * _PROCESS_SALT


def _persistent_cache_paused():
    """Context: de-initialize jax's persistent compilation cache so the
    next compile neither reads nor writes it (config-flag toggles alone do
    not gate an already-initialized cache in jax 0.4.x). Best-effort — the
    reset helper is a private jax API; trace_salt is the version-proof
    backstop."""
    import contextlib as _ctx

    @_ctx.contextmanager
    def scope():
        import jax

        try:
            from jax._src import compilation_cache as _cc
        except Exception:  # noqa: BLE001 — private API; salt still protects
            _cc = None
        old_dir = jax.config.jax_compilation_cache_dir
        if _cc is not None and old_dir:
            jax.config.update("jax_compilation_cache_dir", None)
            _cc.reset_cache()
        try:
            yield
        finally:
            if _cc is not None and old_dir:
                jax.config.update("jax_compilation_cache_dir", old_dir)
                _cc.reset_cache()

    return scope()


@contextlib.contextmanager
def donation_warnings_suppressed():
    """jax warns when donated buffers cannot be consumed (the CPU backend
    ignores donation). The fused paths donate unconditionally — on TPU
    donation is the point (in-place weight updates), on CPU a harmless
    no-op — so their call sites wrap invocations in this scope instead of
    installing a process-global filter that would also silence the signal
    for a user's own jax code."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def persistent_cache_dir():
    """Apply ``MXNET_COMPILE_CACHE_DIR`` to jax's persistent compilation
    cache (idempotent; called at import). Returns the directory or None."""
    path = getenv("MXNET_COMPILE_CACHE_DIR")
    if not path:
        return None
    try:
        import os

        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # small programs compile faster than they deserialize; only big
        # compiles (the ones that hurt through a flaky relay) are persisted
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        return path
    except Exception:  # noqa: BLE001 — the on-disk cache is an optimisation
        return None


def _entries_gauge():
    """Recompute the live-entry gauge over every live cache."""
    with _caches_lock:
        total = sum(len(c) for c in _caches)
    telemetry.gauge("compile.cache_entries").set(total)


class CompileCache:
    """A named map ``key -> compiled callable`` with hit/miss/compile-time
    accounting. ``key`` is any hashable — by convention the full shape
    signature (shape+dtype of every input) plus whatever static
    configuration the builder closes over (train flag, optimizer
    fingerprint), the CachedOp signature-match model."""

    def __init__(self, name, maxsize=None, track_memory=True):
        self.name = name
        self.maxsize = maxsize
        # track_memory=False skips first-call aval recording, keeping this
        # cache OUT of executable_stats()/the /memory scrape — the per-op
        # caches hold hundreds of tiny one-op programs whose per-entry AOT
        # memory analysis would cost a recompile each for no insight
        self.track_memory = track_memory
        self.hits = 0
        self.misses = 0
        self.compile_seconds = 0.0
        self._name_totals = _totals(name)
        self._entries = {}
        # key -> {"avals": first-call abstract shapes, "memory": analysis}
        # (shape/dtype skeletons only — never holds buffers alive)
        self._entry_stats = {}
        self._lock = threading.Lock()
        with _caches_lock:
            _caches.add(self)

    def __len__(self):
        return len(self._entries)

    def keys(self):
        return list(self._entries.keys())

    def get_or_build(self, key, build, persistent=True, audit=None):
        """The cached callable for ``key``; on miss, ``build()`` makes one
        (typically a ``jax.jit`` closure) and its first invocation is timed
        into ``compile.seconds``.

        ``persistent=False`` keeps this program OUT of jax's on-disk
        compilation cache: executables with donated (input-aliased) buffers
        deserialize with broken aliasing on XLA:CPU and corrupt the heap on
        invocation (reproduced: 'corrupted double-linked list' on the second
        process reusing MXNET_COMPILE_CACHE_DIR). The fused train-step and
        fused optimizer-update programs pass False; everything else persists.

        ``audit`` names the hlolint contract row this entry is audited
        under (``MXNET_HLOLINT_DUMP`` / ``tools/hlolint``); it defaults to
        the cache name. The fused train step passes the composition that
        actually built the program ("spmd"/"pipeline"/"zero1"/
        "fused_step") since those share the executor-side caches.
        """
        fn = self._entries.get(key)
        if fn is not None:
            self.hits += 1
            self._name_totals["hits"] += 1
            telemetry.counter("compile.cache_hits").inc()
            if self.maxsize is not None:
                # LRU, not FIFO: refresh position so overflow evicts a COLD
                # entry, never the per-step executable hit every iteration
                with self._lock:
                    if key in self._entries:
                        self._entries[key] = self._entries.pop(key)
            return fn
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self.hits += 1
                self._name_totals["hits"] += 1
                telemetry.counter("compile.cache_hits").inc()
                return fn
            self.misses += 1
            self._name_totals["misses"] += 1
            telemetry.counter("compile.cache_misses").inc()
            if self.hits > 0 and self._entries:
                # a STEADY-STATE miss: this cache has already served hits,
                # so a new key means something about the workload changed —
                # blame the axis instead of burning the budget silently
                _blame_miss(self.name, key, self._entries)
            fn = self._wrap_first_call(build(), persistent, key, audit)
            if self.maxsize is not None and len(self._entries) >= self.maxsize:
                # drop the least-recently-used entry — executables are
                # re-buildable, never precious
                evicted = next(iter(self._entries))
                self._entries.pop(evicted)
                self._entry_stats.pop(evicted, None)
                try:
                    from . import health

                    if health._enabled:
                        # an eviction at steady state means the next use
                        # of that key RECOMPILES — exactly the sequence a
                        # postmortem wants in the journal
                        health.event("compile_cache_evict",
                                     cache=self.name,
                                     entries=len(self._entries))
                except Exception:  # noqa: BLE001 — journal is additive
                    pass
            self._entries[key] = fn
        _entries_gauge()
        return fn

    def _record_avals(self, key, args, kwargs):
        """Shape/dtype skeleton of the first call — enough to re-lower the
        program for XLA memory analysis (`memory_stats`) without keeping a
        single buffer alive."""
        try:
            import jax

            def aval(x):
                if hasattr(x, "shape") and hasattr(x, "dtype"):
                    return jax.ShapeDtypeStruct(x.shape, x.dtype)
                return x

            self._entry_stats[key] = {
                "avals": jax.tree_util.tree_map(aval, (tuple(args),
                                                       dict(kwargs))),
                "memory": None, "cost": None, "collectives": None}
        except Exception:  # noqa: BLE001 — stats are additive, never fatal
            pass

    def entry_memory(self, key, _want_collectives=False):
        """XLA compiled-memory analysis for one entry: {argument_bytes,
        output_bytes, temp_bytes, peak_bytes} or None. Computed LAZILY via
        an AOT `lower().compile()` pass over the recorded avals and
        memoized (failures too); never runs on the step path. NOTE the
        first computation can be a FULL recompile, not just a re-trace:
        the AOT path bypasses jax's jit dispatch cache, and persistent=False
        (donated) entries are deliberately kept out of the on-disk cache —
        budget seconds per entry on the first scrape of a big cache."""
        st = self._entry_stats.get(key)
        if st is None:
            return None
        if st["memory"] is not None and not (
                _want_collectives and st.get("collectives") is None):
            return st["memory"] or None  # False = memoized FAILED analysis
        fn = self._entries.get(key)
        target = getattr(fn, "_fn", fn)
        if not hasattr(target, "lower"):
            return None
        try:
            args, kwargs = st["avals"]
            with donation_warnings_suppressed():
                compiled = target.lower(*args, **kwargs).compile()
            ma = compiled.memory_analysis()
            # the same AOT pass also yields the cost analysis (FLOPs,
            # bytes accessed — the observatory's roofline numerators) for
            # free; the collective inventory needs the full
            # post-optimization HLO TEXT, which is expensive to serialise
            # and parse for big programs, so it is extracted only when
            # entry_collectives asked for it (the /memory scrape sweeps
            # every entry and must stay as cheap as plain memory_analysis)
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                st["cost"] = {
                    "flops": float(ca.get("flops", 0.0)),
                    "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
            except Exception:  # noqa: BLE001 — cost is best-effort
                st["cost"] = False
            if _want_collectives:
                try:
                    from . import analysis

                    kinds, _ = analysis.parse_collectives(compiled.as_text())
                    st["collectives"] = {k: dict(v)
                                         for k, v in kinds.items()}
                except Exception:  # noqa: BLE001 — inventory best-effort
                    st["collectives"] = False
            st["memory"] = {
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
                "alias_bytes": int(ma.alias_size_in_bytes),
                # resident working set while the program runs: inputs +
                # outputs + temporaries, minus buffers aliased in place
                # (donation) — the per-executable peak-HBM estimate
                "peak_bytes": int(ma.argument_size_in_bytes
                                  + ma.output_size_in_bytes
                                  + ma.temp_size_in_bytes
                                  - ma.alias_size_in_bytes)}
        except Exception:  # noqa: BLE001 — analysis is best-effort
            st["memory"] = False  # memoize the failure: the AOT lowering
            st.setdefault("cost", None)
            st["cost"] = st["cost"] or False
            st["collectives"] = st.get("collectives") or False
            return None           # is expensive and will not get better
        return st["memory"]

    def entry_cost(self, key):
        """XLA cost analysis for one entry: ``{flops, bytes_accessed}``
        or None — computed in the SAME lazy AOT pass as
        :meth:`entry_memory` (one lowering feeds memory, cost and
        collective attribution), memoized including failures. The
        observatory's roofline numerators."""
        st = self._entry_stats.get(key)
        if st is None:
            return None
        if st.get("cost") is None:
            self.entry_memory(key)
        return st.get("cost") or None

    def entry_collectives(self, key):
        """Collective inventory of one entry's COMPILED program
        (``{kind: {count, bytes}}``, bytes per participant) or None —
        recorded by the shared AOT pass on demand (an entry first scanned
        by a plain memory scrape pays one extra lowering here); the
        observatory's comm-bound attribution source, same parser as the
        hlolint audit."""
        st = self._entry_stats.get(key)
        if st is None:
            return None
        if st.get("collectives") is None:
            self.entry_memory(key, _want_collectives=True)
        coll = st.get("collectives")
        return coll if coll not in (None, False) else None

    def memory_stats(self, compute=False):
        """Per-entry memory rows for this cache: entries whose analysis
        has been computed (``compute=True`` forces the lazy analysis for
        every entry first). Rows: {key, argument_bytes, ...}."""
        rows = []
        for key in list(self._entry_stats):
            st = self._entry_stats.get(key)
            if st is None:
                continue
            mem = self.entry_memory(key) if compute else st["memory"]
            if mem:  # None = not computed, False = memoized failure
                rows.append(dict(mem, key=repr(key)))
        return rows

    def _wrap_first_call(self, fn, persistent=True, key=None, audit=None):
        cache = self

        class _Timed:
            """First call runs under a timer (trace + XLA compile happen
            synchronously there), with the jax donation warning suppressed
            and — for persistent=False programs — the on-disk compilation
            cache disabled so the executable is neither written nor read
            (see get_or_build); later calls go straight through."""

            __slots__ = ("_fn", "_first")

            def __init__(self):
                self._fn = fn
                self._first = True

            def __call__(self, *args, **kwargs):
                if self._first:
                    t0 = time.perf_counter()
                    with donation_warnings_suppressed():
                        if persistent:
                            out = self._fn(*args, **kwargs)
                        else:
                            # pause the on-disk cache for this one compile
                            # (donated executables must never be persisted
                            # — see get_or_build); compiles are rare and
                            # the cache is restored immediately
                            with _persistent_cache_paused():
                                out = self._fn(*args, **kwargs)
                    # only now: a FAILED first call must retry with the
                    # cache pause + accounting intact (another caller can
                    # hit this shared entry after one caller's trace error)
                    self._first = False
                    if key is not None and cache.track_memory:
                        cache._record_avals(key, args, kwargs)
                    if key is not None and getenv("MXNET_HLOLINT_DUMP"):
                        _audit_record(cache, audit or cache.name, key,
                                      self, args, kwargs)
                    dt = time.perf_counter() - t0
                    cache.compile_seconds += dt
                    cache._name_totals["compile_seconds"] += dt
                    telemetry.counter("compile.seconds").inc(dt)
                    telemetry.histogram("compile.first_call_us").record(dt * 1e6)
                    return out
                return self._fn(*args, **kwargs)

        return _Timed()

    def clear(self):
        with self._lock:
            self._entries.clear()
        _entries_gauge()

    def snapshot(self):
        return {"name": self.name, "entries": len(self._entries),
                "hits": self.hits, "misses": self.misses,
                "compile_seconds": self.compile_seconds}


def all_caches():
    """Live :class:`CompileCache` instances."""
    with _caches_lock:
        return list(_caches)


def stats():
    """Aggregate {entries, hits, misses, compile_seconds} over live caches
    plus a per-cache breakdown (`tools/telemetry_report.py` prints this)."""
    per = [c.snapshot() for c in all_caches()]
    return {"entries": sum(p["entries"] for p in per),
            "hits": sum(p["hits"] for p in per),
            "misses": sum(p["misses"] for p in per),
            "compile_seconds": sum(p["compile_seconds"] for p in per),
            "caches": sorted(per, key=lambda p: p["name"])}


def name_totals():
    """{name: {hits, misses, compile_seconds, entries}} for EVERY cache
    name ever seen — the monotonic per-name ledger behind
    :func:`named_stats`, in one map. ``entries`` counts currently-live
    executables. `telemetry.snapshot()` embeds this as the
    ``compile_caches`` section so op-level (``op_eager``/``op_vjp``),
    segment-level (``lazy``) and subsystem caches all read the same way in
    ``tools/telemetry_report.py``."""
    with _caches_lock:
        totals = {n: dict(t) for n, t in _name_totals.items()}
        live = list(_caches)
    for t in totals.values():
        t["entries"] = 0
    for c in live:
        t = totals.get(c.name)
        if t is not None:
            t["entries"] += len(c)
    return totals


def named_stats(name):
    """The per-subsystem view of :func:`stats` for every cache ever named
    ``name`` (e.g. ``named_stats("serving")`` answers "did steady-state
    traffic compile anything?" without counting the training-side
    executors that share the process). ``hits``/``misses``/
    ``compile_seconds`` are MONOTONIC process-lifetime totals — a
    garbage-collected cache keeps its contribution, so deltas are safe to
    assert on; ``entries``/``caches`` describe the currently-live ones."""
    per = [c.snapshot() for c in all_caches() if c.name == name]
    totals = _totals(name)
    return {"entries": sum(p["entries"] for p in per),
            "hits": totals["hits"],
            "misses": totals["misses"],
            "compile_seconds": totals["compile_seconds"],
            "caches": len(per)}


# ---------------------------------------------------------------------------
# steady-state recompile blamer
# ---------------------------------------------------------------------------
#
# The zero-steady-compile SLO (PR 11: compile.cache_misses rate <= 0 after
# the warmup grace) can only say THAT a warmed cache missed, not WHY. The
# blamer structurally diffs the missing key against its nearest existing
# neighbor and names the axis that changed — shape (batch vs inner dim),
# dtype, optimizer hyperparam, sharding plan, or attr — as a
# `compile_blame` health-journal event and `compile.blamed_misses` /
# `compile.blame_axis.*` counters. "Why did steady state recompile?"
# becomes a named diagnosis instead of folklore debugging.

_BLAME_NEIGHBORS = 64      # newest keys considered as nearest-neighbor
_BLAME_AXES_MAX = 4        # axes reported per event

_DTYPE_NAMES = frozenset(
    "float16 float32 float64 bfloat16 int8 int16 int32 int64 uint8 uint16 "
    "uint32 uint64 bool complex64 complex128".split())

_SHARD_SPEC_RE = None  # compiled lazily (re import stays off the hot path)


def _is_dtype_leaf(v):
    if hasattr(v, "itemsize") and hasattr(v, "name"):     # np.dtype
        return True
    if isinstance(v, type) and getattr(v, "__name__", "") in _DTYPE_NAMES:
        return True
    return isinstance(v, str) and v in _DTYPE_NAMES


def _is_shard_leaf(v, parent):
    """A sharding-plan component: a spec string (`tp=2,fsdp=4`) or any
    leaf of a tuple tagged by its subsystem ("zero1"/"spmd"/"mesh"...)."""
    global _SHARD_SPEC_RE
    if isinstance(parent, tuple) and parent and isinstance(parent[0], str) \
            and parent[0] in ("zero1", "spmd", "mesh", "pipeline"):
        return True
    if not isinstance(v, str):
        return False
    if _SHARD_SPEC_RE is None:
        import re as _re

        _SHARD_SPEC_RE = _re.compile(r"(^|[,(])\s*(tp|fsdp|dp|pp|sp|ep)=")
    return bool(_SHARD_SPEC_RE.search(v))


def _flatten_key(k, path=(), parent=None, out=None):
    """Leaf list [(path, parent_container, value)] of one cache key —
    keys are nested tuples by convention (shape signatures, static
    config), so tuple/list are the only containers walked."""
    if out is None:
        out = []
    if isinstance(k, (tuple, list)):
        for i, v in enumerate(k):
            _flatten_key(v, path + (i,), k, out)
        if not k:
            out.append((path, parent, k))
    else:
        out.append((path, parent, k))
    return out


def _axis_of(path, parent, old, new):
    """Name the key axis a differing leaf belongs to."""
    if _is_dtype_leaf(old) or _is_dtype_leaf(new):
        return "dtype"
    if _is_shard_leaf(old, parent) or _is_shard_leaf(new, parent):
        return "sharding"
    if isinstance(old, bool) or isinstance(new, bool):
        return "attr"
    if isinstance(old, int) and isinstance(new, int):
        if isinstance(parent, (tuple, list)) and parent and all(
                isinstance(x, int) and not isinstance(x, bool)
                for x in parent):
            # an all-int tuple in a cache key is a shape by convention
            # (executor._sig, serving bucket sigs, slab geometry)
            dim = path[-1] if path else 0
            return "shape(batch)" if dim == 0 else f"shape(dim{dim})"
        return "attr"
    if isinstance(old, float) and isinstance(new, float):
        return "hyperparam"
    return "attr"


def _key_distance(a_flat, b_map):
    """(score, diffs): structural mismatches weigh 1000, each differing
    leaf 1, with a <1 numeric-closeness tiebreak so batch 9 blames the
    size-8 bucket, not the size-4 one."""
    diffs = []
    score = 0.0
    seen = set()
    for path, parent, v in a_flat:
        seen.add(path)
        if path not in b_map:
            score += 1000.0
            continue
        bparent, bv = b_map[path]
        eq = False
        try:
            eq = bool(v == bv) and type(v) is type(bv)
        except Exception:  # noqa: BLE001 — exotic leaf comparisons
            eq = v is bv
        if eq:
            continue
        score += 1.0
        if isinstance(v, (int, float)) and isinstance(bv, (int, float)) \
                and not isinstance(v, bool) and not isinstance(bv, bool):
            denom = abs(float(v)) + abs(float(bv)) + 1e-9
            score += min(1.0, abs(float(v) - float(bv)) / denom) * 0.5
        diffs.append((path, parent, bv, v))  # (path, parent, old, new)
    score += 1000.0 * sum(1 for p in b_map if p not in seen)
    return score, diffs


def _blame_miss(cache_name, key, entries):
    """Diff ``key`` against its nearest neighbor among ``entries`` and
    publish the diagnosis. Called under the cache lock on a steady-state
    miss — rare by contract, and cheap next to the compile that follows."""
    try:
        new_flat = _flatten_key(key)
        best = None
        for old_key in list(entries)[-_BLAME_NEIGHBORS:]:
            b_map = {p: (parent, v)
                     for p, parent, v in _flatten_key(old_key)}
            score, diffs = _key_distance(new_flat, b_map)
            if best is None or score < best[0]:
                best = (score, old_key, diffs)
        if best is None:
            return
        _, nearest, diffs = best
        axes = []
        for path, parent, old, new in diffs[:_BLAME_AXES_MAX]:
            axes.append({"axis": _axis_of(path, parent, old, new),
                         "path": "/".join(str(p) for p in path),
                         "old": repr(old)[:80], "new": repr(new)[:80]})
        if not axes:
            # same leaves, different structure (rank change, extra input)
            axes.append({"axis": "structure", "path": "",
                         "old": repr(nearest)[:120],
                         "new": repr(key)[:120]})
        primary = axes[0]["axis"]
        telemetry.counter("compile.blamed_misses").inc()
        safe = primary.replace("(", "_").replace(")", "")
        telemetry.counter(f"compile.blame_axis.{safe}").inc()
        try:
            from . import health

            if health._enabled:
                health.event("compile_blame", cache=cache_name,
                             axis=primary, axes=axes,
                             key=repr(key)[:240],
                             nearest=repr(nearest)[:240])
        except Exception:  # noqa: BLE001 — the journal is additive
            pass
    except Exception:  # noqa: BLE001 — diagnosis must never break a build
        pass


# ---------------------------------------------------------------------------
# hlolint audit ledger (MXNET_HLOLINT_DUMP)
# ---------------------------------------------------------------------------


def _audit_tags():
    raw = str(getenv("MXNET_HLOLINT_CACHES") or "")
    return {s.strip() for s in raw.split(",") if s.strip()}


def _audit_record(cache, tag, key, timed, args, kwargs):
    """Retain one first-called executable (strong ref + aval skeleton)
    for the exit dump. Per-tag capped; dedupes by (tag, repr(key)) so the
    same program warmed by many per-context caches is lowered once."""
    try:
        tags = _audit_tags()
        if tags and tag not in tags:
            return
        import jax

        def aval(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        avals = jax.tree_util.tree_map(aval, (tuple(args), dict(kwargs)))
        cap = int(getenv("MXNET_HLOLINT_MAX_ENTRIES"))
        with _audit_lock:
            lk = (tag, repr(key))
            if lk in _audit_ledger:
                return
            if sum(1 for t, _ in _audit_ledger if t == tag) >= cap:
                return
            _audit_ledger[lk] = {"cache": cache.name, "tag": tag,
                                 "key": repr(key), "fn": timed,
                                 "avals": avals}
            if not _audit_hooked[0]:
                _audit_hooked[0] = True
                import atexit

                atexit.register(_dump_audit_atexit)
    except Exception:  # noqa: BLE001 — auditing must never break a step
        pass


def audit_ledger():
    """The recorded (tag, key) pairs — test/tooling introspection."""
    with _audit_lock:
        return sorted(_audit_ledger)


def dump_audit(dirpath):
    """Summarize every ledger entry (AOT lower + compile — seconds per
    donated entry) and write one JSON dump into ``dirpath`` for
    ``python -m tools.hlolint check``. Returns the file path or None when
    the ledger is empty."""
    from . import analysis

    with _audit_lock:
        recs = list(_audit_ledger.values())
    if not recs:
        return None
    entries = []
    for r in recs:
        try:
            summary = analysis.program_summary(r["fn"], r["avals"])
        except Exception as e:  # noqa: BLE001 — one bad entry can't
            summary = {"error": repr(e)[:240]}   # lose the whole dump
        entries.append({"cache": r["cache"], "tag": r["tag"],
                        "key": r["key"], "summary": summary})
    import json

    _os.makedirs(dirpath, exist_ok=True)
    path = _os.path.join(
        dirpath, f"hlolint-{_os.getpid()}-{_time.time_ns() % 10**9}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": _os.getpid(), "entries": entries}, f, indent=1)
    _os.replace(tmp, path)
    return path


def _dump_audit_atexit():
    try:
        d = getenv("MXNET_HLOLINT_DUMP")
        if d:
            dump_audit(d)
    except Exception:  # noqa: BLE001 — exit hooks never raise
        pass


persistent_cache_dir()
