"""Runtime concurrency analysis: the lock-order recorder (MXNET_DEBUG_SYNC).

The static half of the framework's analysis gate lives in
``tools/tpulint`` (AST checkers over the source tree); this module is the
*runtime* half: a lock acquisition-order recorder that turns the repo's
hardest concurrency rules into machine-checked facts instead of reviewer
folklore. Two deadlock classes have already been paid for by hand — the
cross-graph flush deadlock (PR 10) and the assist-vs-worker delivery race
(PR 12) — and both would have been a one-line report under this recorder.

What it checks, when ``MXNET_DEBUG_SYNC=1``:

* **Lock-order inversions.** Every tracked lock acquisition while another
  tracked lock is held records a directed edge ``held -> acquired`` in a
  process-global order graph. An acquisition that closes a cycle (the
  classic ABBA: thread 1 takes A then B, thread 2 takes B then A) is
  reported with BOTH stacks — the stack that first established the
  opposite ordering and the stack that just inverted it — so the report
  reads like the postmortem you would otherwise reconstruct from a hung
  fleet.
* **Blocking hazards.** Holding any tracked lock while entering an
  operation that can block on *other threads or hosts* — a lazy-segment
  flush (which compiles + runs a whole XLA program), a blocking
  collective barrier, or an engine drain — is a deadlock-in-waiting even
  when today's interleaving happens to work. Call sites mark such
  regions with :func:`check_blocking`; a non-empty held set is reported
  with the held-acquisition stacks and the blocking-entry stack.

Reports surface three ways: ``analysis.*`` telemetry counters (recorded
unconditionally once the gate is on, same discipline as ``compile.*``),
a structured health-journal event when the health layer is live, and the
:func:`report` / :func:`assert_clean` API the concurrency test suites
assert on (``ci/run.sh`` re-runs the serving/generation/lazy/elastic
suites under ``MXNET_DEBUG_SYNC=1`` and fails on any inversion).

Overhead discipline (the PR 7/11 rule: gates cost one attribute read when
off): the gate is evaluated when a lock is *created* — :func:`make_lock`
/ :func:`make_rlock` / :func:`make_condition` return plain
``threading`` primitives when the gate is off, so steady-state code pays
literally nothing, not even a flag check per acquire (pinned by
``test_tpulint.py`` in a fresh subprocess). :func:`check_blocking` call
sites gate on ``analysis._enabled`` (one attribute read) themselves.

Second runtime-analysis half (PR 15): **compiled-program summaries** —
:func:`program_summary` AOT-lowers a cached executable from its recorded
aval skeleton and parses the lowered StableHLO + post-optimization HLO
into a structured record: collective inventory (all-reduce / all-gather /
reduce-scatter / collective-permute counts and byte volumes), donation
audit (which ``tf.aliasing_output``-declared arguments actually got
``input_output_alias`` entries in the compiled module), and per-input
residency (global vs per-device local bytes from the compiled input
shardings). ``tools/hlolint`` enforces per-cache contracts over these
summaries (the blocking CI gate); ``CompileCache`` dumps them at exit
when ``MXNET_HLOLINT_DUMP`` is set. The parsers are pure text analysis —
no jax needed to *read* a summary, only to produce one.
"""
from __future__ import annotations

import sys
import threading

from . import telemetry
from .base import MXNetError, getenv, register_env

__all__ = ["enabled", "enable", "make_lock", "make_rlock", "make_condition",
           "check_blocking", "report", "assert_clean", "reset",
           "format_report",
           # compiled-program summaries (the hlolint substrate)
           "program_summary", "summarize_hlo_text", "parse_donated_args",
           "parse_io_aliases", "parse_collectives", "parse_num_partitions",
           "cache_inventory"]

register_env("MXNET_DEBUG_SYNC", False,
             "record lock acquisition order + blocking hazards; zero cost "
             "when off (locks are plain threading primitives)")

# THE gate — read at lock creation time (and by check_blocking call
# sites). Flipping it at runtime via enable() affects locks created
# afterwards; the CI reruns set the env var so every lock in the process
# is tracked from import.
_enabled = bool(getenv("MXNET_DEBUG_SYNC"))

_STACK_LIMIT = 16

# recorder state — one process-global order graph. _state_lock is a plain
# lock and is never itself tracked; the per-thread `busy` flag keeps the
# recorder's own bookkeeping (telemetry increments, journal writes) from
# re-entering the recorder.
_state_lock = threading.Lock()
_edges = {}        # (a, b) -> {count, held_stack, acquire_stack}
_order = {}        # a -> set of b (a held when b acquired)
_inversions = []   # deduped by unordered lock pair
_inv_seen = set()
_hazards = []      # deduped by (kind, held-name tuple)
_haz_seen = set()
_locks_seen = set()

_tls = threading.local()


def enabled():
    return _enabled


def enable(on=True):
    """Flip the gate at runtime. Only locks created AFTER the flip are
    tracked (module-level locks made at import stay plain) — tests use
    this; production runs set ``MXNET_DEBUG_SYNC=1`` in the environment."""
    global _enabled
    _enabled = bool(on)


def _thread_state():
    st = getattr(_tls, "state", None)
    if st is None:
        st = _tls.state = {"held": [], "busy": False}
    return st


def _stack(skip=2):
    """Lightweight stack capture: (file:line func) strings via a raw frame
    walk — no source-line reads, cheap enough for every tracked acquire."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover — shallow stack
        return []
    out = []
    while f is not None and len(out) < _STACK_LIMIT:
        code = f.f_code
        out.append(f"{code.co_filename}:{f.f_lineno} {code.co_name}")
        f = f.f_back
    return out


def _reaches(src, dst):
    """True when ``dst`` is reachable from ``src`` in the order graph
    (iterative DFS; called under _state_lock)."""
    stack, seen = [src], set()
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_order.get(n, ()))
    return False


def _journal(event_kind, **detail):
    """Best-effort health-journal event (lazy import: health imports this
    module for its own locks)."""
    try:
        from . import health

        if health._enabled:
            health.event(event_kind, **detail)
    except Exception:  # noqa: BLE001 — the journal is additive
        pass


def _record_edge(a_name, a_stack, b_name, b_stack):
    """Called under the caller thread's busy guard; takes _state_lock."""
    if a_name == b_name:
        # two DISTINCT instances sharing a name (every Beacon is
        # "health.beacon", every prefix cache "generation.prefix_cache"):
        # order within a name class cannot be validated by name, and a
        # self-edge would instantly read as a bogus cycle — skip, the
        # same trade lockdep makes for same-class nesting
        return None
    key = (a_name, b_name)
    with _state_lock:
        rec = _edges.get(key)
        if rec is not None:
            rec["count"] += 1
            return None
        _edges[key] = {"count": 1, "held_stack": list(a_stack),
                       "acquire_stack": list(b_stack)}
        _order.setdefault(a_name, set()).add(b_name)
        telemetry.gauge("analysis.lock_edges").set(len(_edges))
        if not _reaches(b_name, a_name):
            return None
        # the new edge closes a cycle: the opposite ordering was already
        # observed. Report once per unordered pair, with both stacks —
        # the first-seen opposite edge's and this acquisition's.
        pair = frozenset((a_name, b_name))
        if pair in _inv_seen:
            return None
        _inv_seen.add(pair)
        rev = _edges.get((b_name, a_name))
        inv = {"first": b_name, "then": a_name,
               "held": a_name, "acquiring": b_name,
               "held_stack": list(a_stack),
               "acquire_stack": list(b_stack),
               "opposite_stack": (list(rev["acquire_stack"])
                                  if rev else []),
               "thread": threading.current_thread().name}
        _inversions.append(inv)
    telemetry.counter("analysis.lock_inversions").inc()
    return inv


def _note_acquire(lock):
    st = _thread_state()
    if st["busy"]:
        return
    st["busy"] = True
    try:
        held = st["held"]
        for entry in held:
            if entry[0] is lock:   # reentrant re-acquire: bump, no edge
                entry[2] += 1
                return
        stack = _stack(skip=3)
        inv = None
        if held:
            for other, other_stack, _n in held:
                got = _record_edge(other.name, other_stack, lock.name,
                                   stack)
                inv = inv or got
        else:
            with _state_lock:
                _locks_seen.add(lock.name)
        held.append([lock, stack, 1])
        if inv is not None:
            _journal("lock_inversion", held=inv["held"],
                     acquiring=inv["acquiring"], thread=inv["thread"])
    finally:
        st["busy"] = False


def _note_release(lock):
    st = _thread_state()
    if st["busy"]:
        return
    held = st["held"]
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            held[i][2] -= 1
            if held[i][2] == 0:
                del held[i]
            return
    # release of a lock acquired before tracking began — ignore


class _TrackedLock:
    """``threading.Lock``/``RLock`` wrapper that feeds the order graph.
    Implements the Condition lock protocol (``_is_owned`` /
    ``_release_save`` / ``_acquire_restore``) so
    ``threading.Condition(_TrackedLock(...))`` keeps bookkeeping balanced
    across ``wait()``."""

    __slots__ = ("name", "_lock", "_reentrant")

    def __init__(self, name, reentrant=False):
        self.name = name
        self._reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()
        with _state_lock:
            _locks_seen.add(name)

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _note_acquire(self)
        return ok

    def release(self):
        _note_release(self)
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        inner = getattr(self._lock, "locked", None)
        if inner is not None:
            return inner()
        # threading.RLock grows locked() only in 3.13 — probe instead so
        # the tracked wrapper stays drop-in on 3.10 (an owned-by-us RLock
        # reports False, same blind spot the acquire-probe always had)
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    # -- Condition lock protocol -------------------------------------------

    def _is_owned(self):
        if self._reentrant:
            return self._lock._is_owned()
        # plain-Lock fallback (what Condition would do itself)
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _release_save(self):
        if not self._reentrant:
            _note_release(self)
            self._lock.release()
            return None
        # fully drop a possibly-recursive hold; remember our bookkeeping
        # count so _acquire_restore can rebuild it
        st = _thread_state()
        count = 0
        for i in range(len(st["held"]) - 1, -1, -1):
            if st["held"][i][0] is self:
                count = st["held"][i][2]
                del st["held"][i]
                break
        return (self._lock._release_save(), count)

    def _acquire_restore(self, state):
        if not self._reentrant:
            self._lock.acquire()
            _note_acquire(self)
            return
        inner, count = state
        self._lock._acquire_restore(inner)
        _note_acquire(self)
        if count > 1:
            st = _thread_state()
            for entry in st["held"]:
                if entry[0] is self:
                    entry[2] = count
                    break

    def __repr__(self):
        return f"<TrackedLock {self.name!r} reentrant={self._reentrant}>"


# ---------------------------------------------------------------------------
# factories — THE api instrumented modules use
# ---------------------------------------------------------------------------


def make_lock(name):
    """A mutex for subsystem ``name`` ("generation.tick"): plain
    ``threading.Lock`` when the gate is off, tracked when on."""
    if _enabled:
        return _TrackedLock(name)
    return threading.Lock()


def make_rlock(name):
    """Reentrant variant; only the outermost acquire records an edge."""
    if _enabled:
        return _TrackedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name):
    """``threading.Condition`` whose underlying lock is tracked; ``wait``
    releases/re-acquires through the recorder so held-state stays exact."""
    if _enabled:
        return threading.Condition(_TrackedLock(name, reentrant=True))
    return threading.Condition()


def check_blocking(kind, exempt=()):
    """Record a blocking hazard if this thread holds any tracked lock
    while entering blocking region ``kind`` ("lazy.flush",
    "collective.barrier", "engine.wait_all"). ``exempt`` lists lock
    objects that are legitimately held (e.g. the lazy graph's own lock
    around its flush). Call sites gate on ``analysis._enabled`` first."""
    if not _enabled:
        return None
    st = _thread_state()
    if st["busy"]:
        return None
    held = [e for e in st["held"] if e[0] not in exempt]
    if not held:
        return None
    st["busy"] = True
    try:
        names = tuple(e[0].name for e in held)
        stack = _stack(skip=2)
        with _state_lock:
            key = (kind, names)
            if key in _haz_seen:
                for h in _hazards:
                    if h["kind"] == kind and tuple(h["held"]) == names:
                        h["count"] += 1
                        break
                return None
            _haz_seen.add(key)
            haz = {"kind": kind, "held": list(names), "count": 1,
                   "held_stacks": [list(e[1]) for e in held],
                   "blocking_stack": stack,
                   "thread": threading.current_thread().name}
            _hazards.append(haz)
        telemetry.counter("analysis.blocking_hazards").inc()
        _journal("lock_blocking_hazard", kind=kind, held=list(names))
        return haz
    finally:
        st["busy"] = False


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


def report():
    """Snapshot: {enabled, locks, edges, inversions, hazards}. ``edges``
    is the observed acquisition-order list (a, b, count); ``inversions``
    and ``hazards`` carry both stacks each (see module docstring)."""
    with _state_lock:
        return {
            "enabled": _enabled,
            "locks": sorted(_locks_seen),
            "edges": sorted((a, b, rec["count"])
                            for (a, b), rec in _edges.items()),
            "inversions": [dict(i) for i in _inversions],
            "hazards": [dict(h) for h in _hazards],
        }


def clean():
    """True when no inversion or blocking hazard has been recorded."""
    with _state_lock:
        return not _inversions and not _hazards


def format_report(rep=None):
    """Human-readable rendering of :func:`report` — what the CI rerun
    prints on failure and what `tools/telemetry_report.py` summarizes."""
    rep = rep or report()
    lines = [f"lock-order analysis: {len(rep['locks'])} locks, "
             f"{len(rep['edges'])} order edges, "
             f"{len(rep['inversions'])} inversions, "
             f"{len(rep['hazards'])} blocking hazards"]
    for inv in rep["inversions"]:
        lines.append(f"\nINVERSION: held {inv['held']!r} while acquiring "
                     f"{inv['acquiring']!r} (thread {inv['thread']}), but "
                     f"the opposite order {inv['acquiring']!r} -> "
                     f"{inv['held']!r} was already established")
        lines.append("  stack holding %r:" % inv["held"])
        lines.extend("    " + s for s in inv["held_stack"][:8])
        lines.append("  stack acquiring %r:" % inv["acquiring"])
        lines.extend("    " + s for s in inv["acquire_stack"][:8])
        if inv["opposite_stack"]:
            lines.append("  stack that established the opposite order:")
            lines.extend("    " + s for s in inv["opposite_stack"][:8])
    for haz in rep["hazards"]:
        lines.append(f"\nBLOCKING HAZARD: {haz['held']} held entering "
                     f"{haz['kind']!r} (thread {haz['thread']}, "
                     f"seen {haz['count']}x)")
        lines.append("  blocking-entry stack:")
        lines.extend("    " + s for s in haz["blocking_stack"][:8])
        for name, st in zip(haz["held"], haz["held_stacks"]):
            lines.append(f"  stack holding {name!r}:")
            lines.extend("    " + s for s in st[:8])
    return "\n".join(lines)


def assert_clean():
    """Raise :class:`MXNetError` with the full report when any inversion
    or hazard was recorded — the concurrency suites' session-end check."""
    if not clean():
        raise MXNetError("lock-order analysis found violations:\n"
                         + format_report())


def reset():
    """Clear the order graph and reports (tests; the per-thread held
    stacks are left alone — live locks stay balanced)."""
    with _state_lock:
        _edges.clear()
        _order.clear()
        _inversions.clear()
        _inv_seen.clear()
        _hazards.clear()
        _haz_seen.clear()
        _locks_seen.clear()


# ===========================================================================
# Compiled-program summaries — the hlolint substrate (PR 15)
# ===========================================================================
#
# tpulint checks what we WROTE; these helpers check what XLA actually
# COMPILED. The repo's two worst recent bugs (the jax-0.4.37
# mixed-sharded-concat miscompile and the pipeline grad-scaling bug)
# lived exclusively in the lowered program, and every 1/N-bytes claim in
# ROADMAP is asserted by measuring buffers — a program summary makes the
# same contracts checkable from the executable itself.

import re as _re

# dtype token -> bytes per element, the HLO shape-token vocabulary
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all")

# `%x = f32[64,8]{1,0} all-gather(...)` or a tuple-shaped result
# `%x = (f32[64,8]{1,0}, f32[4]{0}) all-reduce-start(...)`. The optional
# -start suffix counts the async form once; -done deliberately does not
# match (it would double-count).
_COLL_RE = _re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\(")

_SHAPE_RE = _re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")

# one `{out_index}: (param, {param_index}, kind)` pair in the HloModule
# header's input_output_alias map
_ALIAS_RE = _re.compile(
    r"\{([0-9, ]*)\}:\s*\(([0-9]+),\s*\{[0-9, ]*\},\s*([a-z-]+)\)")

# `%arg3: tensor<8x4xf32> {tf.aliasing_output = 0 : i32}` in the
# lowered StableHLO @main signature (the tensor type is captured so the
# donation audit can size each declared argument WITHOUT trusting any
# aval alignment — jax drops unused args from the lowering, which shifts
# every later index). The attr-dict matcher must cross braces inside
# QUOTED values: a donated arg with an explicit layout lowers as
# `{mhlo.sharding = "{devices=[4,1]<=[4]}", tf.aliasing_output = 0 :
# i32}`, and a naive [^{}]* group would drop the donation marker of
# exactly the sharded programs the audit exists to protect.
_STABLEHLO_ARG_RE = _re.compile(
    r"%arg(\d+):\s*tensor<([^>]*)>\s*(\{(?:[^{}\"]+|\"[^\"]*\")*\})?")

_MLIR_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "f8E4M3FN": 1, "f8E5M2": 1,
}


def _mlir_tensor_bytes(type_str):
    """Byte size of one MLIR tensor type string (``8x4xf32`` -> 128;
    scalar ``f32`` -> 4; unknown/dynamic dims count large so a failed
    parse is never silently excused)."""
    parts = type_str.strip().split("x")
    dtype = parts[-1]
    n = 1
    for d in parts[:-1]:
        if not d.isdigit():
            return 1 << 62
        n *= int(d)
    return n * _MLIR_DTYPE_BYTES.get(dtype, 4)


def _shape_token_bytes(token):
    """Byte size of one HLO shape token (``f32[64,8]{1,0}`` -> 2048;
    tuples sum their components; unknown dtypes count 4)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(token):
        n = 1
        if dims:
            for d in dims.split(","):
                if d.strip():
                    n *= int(d)
        total += n * _HLO_DTYPE_BYTES.get(dt, 4)
    return total


def parse_collectives(hlo_text, max_lines=24):
    """Collective inventory of one post-optimization HLO module:
    ``{kind: {"count": n, "bytes": total}}`` plus up to ``max_lines``
    trimmed op lines (the ``--explain`` evidence). Bytes are the op's
    RESULT shape — the per-participant payload the collective moves."""
    kinds = {}
    lines = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        shape_tok, kind = m.group(1), m.group(2)
        ent = kinds.setdefault(kind, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += _shape_token_bytes(shape_tok)
        if len(lines) < max_lines:
            lines.append(line.strip()[:240])
    return kinds, lines


def parse_io_aliases(hlo_text):
    """The compiled module's ``input_output_alias`` entries from the
    HloModule header line: ``[{"output": "0", "param": 2, "kind":
    "may-alias"}, ...]`` — the ground truth of which donations actually
    aliased."""
    for line in hlo_text.splitlines():
        if "input_output_alias=" not in line:
            continue
        start = line.index("input_output_alias=")
        return [{"output": out.strip(), "param": int(param), "kind": kind}
                for out, param, kind in _ALIAS_RE.findall(line[start:])]
    return []


def parse_donated_args(stablehlo_text):
    """Declared donations in the lowered StableHLO ``@main`` signature:
    ``{arg_index: {"output": aliased_output_or_None, "bytes": n}}``.
    ``tf.aliasing_output`` marks an argument jax pre-matched to an
    output; ``jax.buffer_donor`` marks a donated buffer left for XLA to
    alias at compile time. A donation that produced NEITHER marker was
    dropped at lowering (the silent 2x-memory case); whether a marked one
    actually aliased is answered by the compiled module's
    ``input_output_alias`` header (:func:`parse_io_aliases`)."""
    start = stablehlo_text.find("@main(")
    if start < 0:
        return {}
    end = stablehlo_text.find(" {\n", start)
    region = stablehlo_text[start:end if end > 0 else len(stablehlo_text)]
    out = {}
    for idx, type_str, attrs in _STABLEHLO_ARG_RE.findall(region):
        if not attrs:
            continue
        m = _re.search(r"tf\.aliasing_output\s*=\s*(\d+)", attrs)
        if m is not None:
            out[int(idx)] = {"output": int(m.group(1)),
                             "bytes": _mlir_tensor_bytes(type_str)}
        elif "jax.buffer_donor" in attrs:
            out[int(idx)] = {"output": None,
                             "bytes": _mlir_tensor_bytes(type_str)}
    return out


def summarize_hlo_text(stablehlo_text, hlo_text):
    """Structured summary of one lowered+compiled program (pure text
    parsing — callable on dumped artifacts without jax)."""
    collectives, lines = parse_collectives(hlo_text)
    declared = parse_donated_args(stablehlo_text)
    aliased = parse_io_aliases(hlo_text)
    aliased_params = {a["param"] for a in aliased}
    unaliased = sorted(i for i in declared if i not in aliased_params)
    return {
        "collectives": collectives,
        "collective_bytes": sum(v["bytes"] for v in collectives.values()),
        "collective_lines": lines,
        "donation": {
            "declared": sorted(declared),
            # JSON object keys are strings — keep them so a dumped
            # summary and a live one read identically
            "declared_bytes": {str(i): d["bytes"]
                               for i, d in declared.items()},
            "aliased": aliased,
            "unaliased": unaliased,
        },
    }


def _input_rows(avals, shardings):
    """Per-input residency rows: global bytes from the recorded aval
    skeleton, replication + per-device local bytes from the compiled
    input shardings (aligned leaf-by-leaf over the SAME tree structure;
    an UNSPECIFIED sharding is ``None``, which is a pytree-empty value —
    it must be kept as a leaf or every later input's sharding shifts).
    A residual mismatch degrades to global-only rows."""
    import jax

    def keep(x):
        # None (unspecified sharding / empty state slot) stays positional
        return x is None or not isinstance(x, (list, tuple, dict))

    aval_all = jax.tree_util.tree_leaves(avals, is_leaf=keep)
    shard_leaves = []
    if shardings is not None:
        try:
            shard_leaves = jax.tree_util.tree_leaves(shardings,
                                                     is_leaf=keep)
        except Exception:  # noqa: BLE001 — residency rows are best-effort
            shard_leaves = []
    if len(shard_leaves) != len(aval_all):
        shard_leaves = [None] * len(aval_all)
    pairs = [(a, s) for a, s in zip(aval_all, shard_leaves)
             if hasattr(a, "shape") and hasattr(a, "dtype")]
    rows = []
    for a, s in pairs:
        n = 1
        for d in a.shape:
            n *= int(d)
        nbytes = n * a.dtype.itemsize
        row = {"shape": tuple(int(d) for d in a.shape),
               "dtype": str(a.dtype), "bytes": int(nbytes)}
        if s is not None and hasattr(s, "device_set"):
            try:
                row["replicated"] = bool(s.is_fully_replicated)
                local = s.shard_shape(a.shape)
                ln = 1
                for d in local:
                    ln *= int(d)
                row["local_bytes"] = int(ln * a.dtype.itemsize)
                row["devices"] = len(s.device_set)
            except Exception:  # noqa: BLE001 — exotic sharding types
                pass
        rows.append(row)
    return rows


_NUM_PARTITIONS_RE = _re.compile(r"num_partitions\s*=\s*(\d+)")


def parse_num_partitions(stablehlo_text):
    """The SPMD partition count from the lowered module's
    ``mhlo.num_partitions`` attribute (1 when absent) — the authoritative
    device count of the compiled program, independent of input-sharding
    introspection."""
    m = _NUM_PARTITIONS_RE.search(stablehlo_text)
    return int(m.group(1)) if m else 1


def program_summary(fn, avals):
    """AOT-lower one cached executable from its recorded aval skeleton
    and summarize the compiled program: collective inventory, donation
    audit, per-input residency, device count. ``fn`` may be the
    ``CompileCache`` first-call wrapper (its ``_fn`` is unwrapped) or a
    bare ``jax.jit`` callable; ``avals`` is ``(args, kwargs)`` of
    ``ShapeDtypeStruct``\\ s.

    NOTE the lowering is a FULL recompile for donated entries (they are
    deliberately excluded from jax's on-disk cache — PR 3), so this never
    runs on a step path: only the ``MXNET_HLOLINT_DUMP`` exit hook, the
    bench inventory stamp, and tests call it."""
    from . import compile_cache as _cc

    target = getattr(fn, "_fn", fn)
    if not hasattr(target, "lower"):
        return {"error": "unlowerable (no .lower on target)"}
    args, kwargs = avals
    with _cc.donation_warnings_suppressed():
        with _cc._persistent_cache_paused():
            lowered = target.lower(*args, **kwargs)
            stablehlo_text = lowered.as_text()
            compiled = lowered.compile()
            hlo_text = compiled.as_text()
    summary = summarize_hlo_text(stablehlo_text, hlo_text)
    shardings = None
    try:
        shardings = compiled.input_shardings
    except Exception:  # noqa: BLE001 — residency degrades, audit survives
        pass
    summary["inputs"] = _input_rows((args, kwargs), shardings)
    summary["num_devices"] = max(
        [parse_num_partitions(stablehlo_text)]
        + [r.get("devices", 1) for r in summary["inputs"]])
    return summary


def cache_inventory(name):
    """Aggregate collective inventory over every LIVE
    :class:`~mxnet_tpu.compile_cache.CompileCache` named ``name``, from
    each entry's recorded first-call avals (``track_memory=True`` caches
    only). Re-lowers (and for donated entries recompiles) each program —
    bench/report tooling, never a step path. Returns ``{"entries": n,
    "collective_bytes": total, "collectives": {kind: {count, bytes}},
    "errors": n}``."""
    from . import compile_cache as _cc

    agg, total, entries, errors = {}, 0, 0, 0
    for cache in _cc.all_caches():
        if cache.name != name:
            continue
        for key in list(cache._entry_stats):
            st = cache._entry_stats.get(key)
            fn = cache._entries.get(key)
            if st is None or fn is None:
                continue
            try:
                summary = program_summary(fn, st["avals"])
            except Exception:  # noqa: BLE001 — inventory is best-effort
                errors += 1
                continue
            if "error" in summary:
                errors += 1
                continue
            entries += 1
            total += summary["collective_bytes"]
            for kind, v in summary["collectives"].items():
                ent = agg.setdefault(kind, {"count": 0, "bytes": 0})
                ent["count"] += v["count"]
                ent["bytes"] += v["bytes"]
    return {"entries": entries, "collective_bytes": total,
            "collectives": agg, "errors": errors}
