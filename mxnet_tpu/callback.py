"""Training callbacks.

Parity: `python/mxnet/callback.py` — module_checkpoint (:27), do_checkpoint
(:55), log_train_metric (:87), Speedometer (:120), ProgressBar.
"""
from __future__ import annotations

import math
import sys
import time

from . import log as _log

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric", "Speedometer",
           "ProgressBar", "LogValidationMetricsCallback"]


def _logger():
    """Training-progress logger: the same `log.get_logger` stream the
    telemetry summaries use, so one logging config governs both. Level
    NOTSET = inherit the root's effective level — exactly the visibility
    the old root-logger `logging.info` calls had (silent until the user
    raises the level with `logging.basicConfig(level=INFO)`, silenced
    again by `level=ERROR`)."""
    return _log.get_logger("mxnet_tpu.callback", level=_log.NOTSET)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint

            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                _logger().info("Iter[%d] Batch[%d] Train-%s=%f", param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Throughput logging callback (parity callback.py:120)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset
        # slow-step flight recorder (MXNET_TRACING=1): per log interval,
        # keep the worst step's span tree — "p99 got worse" comes with
        # "and here is what that step did"
        self.worst_step = None

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                try:
                    speed = self.frequent * self.batch_size / (time.time() - self.tic)
                except ZeroDivisionError:
                    speed = float("inf")
                # per-step latency quantiles from the telemetry breakdown
                # (BatchEndParam.step_stats, set by fit when MXNET_TELEMETRY=1);
                # the quantile sort runs HERE, once per log tick, not per batch
                stats = getattr(param, "step_stats", None)
                lat = ""
                lat_args = ()
                if stats and stats.get("hist") is not None:
                    p50_us, p99_us = stats["hist"].quantiles(50, 99)
                    if p50_us is not None:
                        lat = "\tstep-p50: %.1f ms\tstep-p99: %.1f ms"
                        lat_args = (p50_us / 1e3, p99_us / 1e3)
                from . import tracing

                if tracing._enabled:
                    # drain the flight recorder: this log interval's worst
                    # step tree, kept for dumps/debuggers until the next
                    # tick; the slowest PHASE is named inline in the log
                    worst = tracing.flight_recorder.worst(reset=True)
                    if worst is not None:
                        self.worst_step = worst
                        kids = worst.get("children") or []
                        if kids:
                            slow = max(kids, key=lambda c: c.get("dur") or 0)
                            lat += "\tworst-step: %.1f ms (%s %.1f ms)"
                            lat_args += ((worst.get("dur") or 0) / 1e3,
                                         slow["name"],
                                         (slow.get("dur") or 0) / 1e3)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    _logger().info(msg + lat, param.epoch, count, speed,
                                   *(sum(name_value, ()) + lat_args))
                else:
                    _logger().info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec" + lat,
                        param.epoch, count, speed, *lat_args)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        sys.stdout.write(f"[{prog_bar}] {percents}%\r")


class LogValidationMetricsCallback:
    """Log validation metrics at each epoch end (parity `callback.py`
    LogValidationMetricsCallback)."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            _logger().info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                           value)
