"""Monitor — per-op output statistics for debugging.

Parity: `python/mxnet/monitor.py` (installs executor monitor callbacks via
`MXExecutorSetMonitorCallbackEX`, `graph_executor.cc:115`). Here executors
call :meth:`Monitor.tic_tac` around node evaluation when installed.
"""
from __future__ import annotations

import re

from . import log as _log
from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False, monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                return x.norm() / (x.size ** 0.5)

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

    def stat_helper(self, name, value):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(value)))

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper, self.monitor_all)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            for v in v_list:
                res.append((n, k, str(v.asscalar() if v.size == 1 else v.asnumpy())))
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        self.queue = []
        return res

    def toc_print(self):
        # routed through log.get_logger (not the root logger) so monitor
        # stats share the training/telemetry stream and its config; NOTSET
        # inherits the root level — the old root `logging.info` visibility
        logger = _log.get_logger("mxnet_tpu.monitor", level=_log.NOTSET)
        res = self.toc()
        for n, k, v in res:
            logger.info("Batch: %7d %30s %s", n, k, v)
