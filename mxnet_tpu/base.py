"""Core shared pieces: error type, dtype maps, registries, env config.

TPU-native re-design of the reference's binding base
(`python/mxnet/base.py`, `3rdparty/dmlc-core` GetEnv / Parameter reflection).
There is no ctypes ABI here by design: the "C API" layer of the reference
(`src/c_api/`, ~212 functions) existed to bridge Python to a C++ kernel
runtime; in this framework the kernel runtime *is* XLA, reached through jax.
The native C++ runtime (engine / recordio / shm storage in `src/`) is loaded
lazily via :mod:`mxnet_tpu.lib` instead.
"""
from __future__ import annotations

import os
import threading

import numpy as _np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "integer_types",
    "data_dir",
    "getenv",
    "setenv",
]


class MXNetError(RuntimeError):
    """Default error raised by mxnet_tpu (name kept for API parity with the
    reference's ``mxnet.base.MXNetError``, `python/mxnet/base.py:78`)."""


class NotImplementedForSymbol(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__()
        self.function = function.__name__
        self.alias = alias

    def __str__(self):
        return f"Function {self.function} is not implemented for Symbol and only available in NDArray."


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__()
        self.function = function.__name__
        self.alias = alias

    def __str__(self):
        return f"Function {self.function} is not supported for SparseNDArray."


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# ---------------------------------------------------------------------------
# dtype handling.  The reference maps type-flag ints across the C ABI
# (`python/mxnet/base.py` _DTYPE_NP_TO_MX / _DTYPE_MX_TO_NP); we keep the same
# flag numbering for serialization-format compatibility.
# ---------------------------------------------------------------------------

_DTYPE_NP_TO_MX = {
    None: -1,
    _np.float32: 0,
    _np.float64: 1,
    _np.float16: 2,
    _np.uint8: 3,
    _np.int32: 4,
    _np.int8: 5,
    _np.int64: 6,
    _np.bool_: 7,
}

_DTYPE_MX_TO_NP = {
    -1: None,
    0: _np.float32,
    1: _np.float64,
    2: _np.float16,
    3: _np.uint8,
    4: _np.int32,
    5: _np.int8,
    6: _np.int64,
    7: _np.bool_,
}

# TPU-native extension: bfloat16 is first-class on the MXU.
try:  # pragma: no cover - ml_dtypes ships with jax
    import ml_dtypes as _ml_dtypes

    _DTYPE_NP_TO_MX[_ml_dtypes.bfloat16] = 12
    _DTYPE_MX_TO_NP[12] = _ml_dtypes.bfloat16
    bfloat16 = _np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    bfloat16 = None

_STORAGE_TYPE_STR_TO_ID = {"undefined": -1, "default": 0, "row_sparse": 1, "csr": 2}
_STORAGE_TYPE_ID_TO_STR = {v: k for k, v in _STORAGE_TYPE_STR_TO_ID.items()}


def np_dtype(dtype):
    """Canonicalize a dtype-ish value to a numpy dtype (bfloat16-aware).
    64-bit types narrow to 32-bit unless jax x64 is enabled (jax semantics;
    the reference's int64 large-tensor build maps to enabling x64)."""
    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16" and bfloat16 is not None:
        return bfloat16
    dt = _np.dtype(dtype)
    try:
        from jax import config as _jcfg

        x64 = _jcfg.jax_enable_x64
    except Exception:
        x64 = False
    if not x64:
        if dt == _np.int64:
            return _np.dtype(_np.int32)
        if dt == _np.float64:
            return _np.dtype(_np.float32)
        if dt == _np.uint64:
            return _np.dtype(_np.uint32)
    return dt


# ---------------------------------------------------------------------------
# Env config registry: the TPU-era answer to dmlc::GetEnv + docs/faq/env_var.md.
# Knobs keep their MXNET_* names where they still make sense.
# ---------------------------------------------------------------------------

_env_lock = threading.Lock()
_env_registry = {}


def register_env(name, default, doc=""):
    with _env_lock:
        _env_registry[name] = (default, doc)
    return name


def getenv(name, default=None):
    if default is None and name in _env_registry:
        default = _env_registry[name][0]
    val = os.environ.get(name)
    if val is None:
        return default
    if isinstance(default, bool):
        return val not in ("0", "false", "False", "")
    if isinstance(default, int):
        return int(val)
    if isinstance(default, float):
        return float(val)
    return val


def setenv(name, value):
    os.environ[name] = str(value)


def list_env():
    """All registered config knobs → (default, doc)."""
    return dict(_env_registry)


register_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice", "host-side engine impl")
register_env("MXNET_CPU_WORKER_NTHREADS", 1, "host worker threads")
register_env("MXNET_EXEC_BULK_EXEC_INFERENCE", True, "fuse inference graphs (always on: XLA)")
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", True, "fuse training graphs (always on: XLA)")
register_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000, "kept for API parity")
register_env("MXNET_BACKWARD_DO_MIRROR", False, "rematerialize activations (jax.checkpoint)")
register_env("MXNET_SAFE_ACCUMULATION", True, "accumulate reductions in fp32")


def data_dir():
    """Data directory used by gluon datasets (parity: `python/mxnet/base.py data_dir`)."""
    return os.getenv("MXNET_HOME", os.path.join(os.path.expanduser("~"), ".mxnet"))


# ---------------------------------------------------------------------------
# Generic registry helper (parity: dmlc Registry / python/mxnet/registry.py)
# ---------------------------------------------------------------------------


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


class classproperty:
    def __init__(self, f):
        self.f = f

    def __get__(self, obj, owner):
        return self.f(owner)
