"""Resilience layer: transient-fault retry, checkpoint-integrity errors,
and a deterministic fault-injection harness.

The reference stack survives preemption through `save_checkpoint` /
`load_checkpoint` and an engine that aborts loudly on op failure
(`threaded_engine.cc` ExecuteOprBlock error path). This module is the
TPU-era rendering of that contract for the host-side IO plane, where the
real faults live (flaky NFS/GCS mounts, torn writes on preemption, wedged
prefetch threads):

* :func:`retry_call` / :func:`wrap_retry` — jittered exponential backoff
  with a bounded retry budget for idempotent IO (checkpoint payload
  writes, recordio/image opens, indexed reads, shm attach).
* :class:`CorruptCheckpointError` — raised by `nd.load` when a CRC32/length
  footer does not match; `model.load_checkpoint` catches it to fall back
  to the last good epoch.
* :func:`inject` — fault points compiled from ``MXNET_FAULT_SPEC`` so tests
  can prove recovery deterministically: fail the nth open of `*.params`
  with EIO, truncate a checkpoint write at K bytes, kill a prefetch
  thread. Zero overhead when the spec is empty (one cached-string check).

``MXNET_FAULT_SPEC`` grammar — rules separated by ``;``, ``key=value``
fields separated by ``,``::

    point=open,path=*.params,nth=2,error=EIO
    point=write,path=*-0002.params,truncate=64
    point=prefetch,error=KILL
    point=write,path=*.params,times=3,error=EIO
    point=publish,path=*.manifest.json,error=CORRUPT

Fields: ``point`` (open|read|write|prefetch|shm|publish — required),
``path`` (fnmatch pattern, default ``*``), ``nth`` (first matching event
to fault, 1-based, default 1), ``times`` (how many consecutive events to
fault, ``inf`` allowed, default 1), ``error`` (errno name, default EIO;
``KILL`` raises :class:`ThreadKilled`), ``truncate`` (byte count — the
write lands but is cut at K bytes, a torn write).

The ``publish`` point covers a weight-rollout publish
(``serving.rollout.publish``) end to end. Errno rules raise as usual;
three publish-only self-inflicted modes return the rule for the
publisher to enact on its own output: ``truncate=K`` tears the manifest
at K bytes (torn rename), ``error=CORRUPT`` flips a payload byte after
the CRC footers land, and ``error=STALE`` stamps the manifest with an
already-published version number — the pathologies the rollout
subscriber's reject-and-keep-serving path is tested against.
"""
from __future__ import annotations

import errno as _errno
import fnmatch
import os
import random
import threading
import time

from .base import MXNetError, getenv, register_env
from .log import get_logger

__all__ = ["CorruptCheckpointError", "ThreadKilled", "WorkerLostError",
           "FaultRule", "retry_call", "wrap_retry", "open_checked",
           "inject", "fault_scope", "reset_fault_counters",
           "durable_replace"]


def durable_replace(tmp, dst):
    """Atomically publish a fully-written (and fsync'd) temp file: rename,
    then fsync the containing directory so a host crash right after cannot
    lose the rename itself. The shared tail of every atomic writer here
    (checkpoint payloads, telemetry snapshots)."""
    os.replace(tmp, dst)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(dst)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platform without directory fsync

register_env("MXNET_IO_RETRY_BUDGET", 3, "retries after the first failed IO attempt")
register_env("MXNET_IO_RETRY_BACKOFF", 0.05, "initial retry backoff seconds")
register_env("MXNET_IO_RETRY_BACKOFF_MAX", 2.0, "retry backoff ceiling seconds")
register_env("MXNET_CHECKPOINT_VERIFY", True, "verify per-array CRC32 footers on load")
register_env("MXNET_CHECKPOINT_KEEP", 0, "retain only the newest K epoch .params files (0 = all)")
register_env("MXNET_FAULT_SPEC", "", "deterministic IO fault-injection spec (tests)")
register_env("MXNET_PREFETCH_JOIN_TIMEOUT", 5.0, "seconds to wait for a prefetch thread at reset")
register_env("MXNET_BARRIER_WARN_S", 60.0, "dist barrier slower than this logs a straggler warning")
register_env("MXNET_INIT_TIMEOUT_S", 0, "bound on jax.distributed rendezvous (0 = jax default)")


class CorruptCheckpointError(MXNetError):
    """A saved array file failed integrity verification (bad CRC, short
    read, or torn payload)."""


class ThreadKilled(Exception):
    """Injected 'thread dies silently' fault (``error=KILL``)."""


class WorkerLostError(MXNetError):
    """A peer worker's heartbeat lease expired while this rank sat in (or
    failed out of) a collective — the structured form of the dist-barrier
    straggler stall. Raised by `parallel.elastic.ElasticRuntime.guard`
    instead of blocking forever; carries the lost ranks so the shrink
    rendezvous knows the surviving membership."""

    def __init__(self, desc, lost_ranks, cause=None):
        self.desc = desc
        self.lost_ranks = tuple(sorted(lost_ranks))
        self.cause = cause
        msg = (f"worker(s) {list(self.lost_ranks)} lost during {desc} "
               f"(heartbeat lease expired)")
        if cause is not None:
            msg += f"; collective error: {cause!r}"
        super().__init__(msg)


def _logger():
    return get_logger("mxnet_tpu.resilience")


# ---------------------------------------------------------------------------
# Retry with jittered exponential backoff
# ---------------------------------------------------------------------------

# deterministic outcomes a retry can never change: replaying an open of a
# missing path (or a permission wall) just burns the backoff budget and
# floods the log with bogus "transient" warnings
_NO_RETRY_ERRNOS = frozenset(
    getattr(_errno, name) for name in
    ("ENOENT", "EISDIR", "ENOTDIR", "EACCES", "EPERM", "EROFS", "ENAMETOOLONG")
    if hasattr(_errno, name))


def retry_call(fn, *args, desc=None, retries=None, backoff=None,
               backoff_max=None, retry_on=(OSError,), **kwargs):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception retry up to
    ``retries`` more times, sleeping ``backoff * 2**attempt`` (jittered to
    50–100%, capped at ``backoff_max``) between attempts. Deterministic
    OSErrors (missing file, permissions) raise immediately. Only use for
    idempotent operations — a replayed write/open must be harmless."""
    retries = getenv("MXNET_IO_RETRY_BUDGET") if retries is None else retries
    backoff = getenv("MXNET_IO_RETRY_BACKOFF") if backoff is None else backoff
    backoff_max = (getenv("MXNET_IO_RETRY_BACKOFF_MAX")
                   if backoff_max is None else backoff_max)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            from . import telemetry

            if isinstance(e, OSError) and e.errno in _NO_RETRY_ERRNOS:
                raise
            if attempt >= retries:
                if telemetry._enabled:
                    telemetry.counter("io.retry_exhausted").inc()
                raise
            if telemetry._enabled:
                telemetry.counter("io.retries").inc()
            delay = min(backoff * (2 ** attempt), backoff_max)
            delay *= 0.5 + 0.5 * random.random()
            attempt += 1
            _logger().warning(
                "transient IO failure on %s (attempt %d/%d, retrying in %.3fs): %s",
                desc or getattr(fn, "__name__", "?"), attempt, retries, delay, e)
            time.sleep(delay)


def wrap_retry(fn, desc=None, retries=None):
    """``fn`` wrapped in :func:`retry_call` (for handing to `engine.push`)."""
    def run(*args, **kwargs):
        return retry_call(fn, *args, desc=desc, retries=retries, **kwargs)
    run.__name__ = getattr(fn, "__name__", "wrapped")
    return run


def open_checked(path, mode="rb"):
    """`open` with the ``open`` fault point and transient-fault retry —
    the entry point for recordio/image file opens."""
    def attempt():
        inject("open", path)
        return open(path, mode)
    return retry_call(attempt, desc=f"open {path}")


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

class FaultRule:
    """One compiled ``MXNET_FAULT_SPEC`` rule + its event counter."""

    __slots__ = ("point", "path", "nth", "times", "error", "truncate", "count")

    def __init__(self, point, path="*", nth=1, times=1, error="EIO",
                 truncate=None):
        if point not in ("open", "read", "write", "prefetch", "shm",
                         "publish"):
            raise MXNetError(f"MXNET_FAULT_SPEC: unknown fault point {point!r}")
        if error in ("CORRUPT", "STALE"):
            if point != "publish":
                raise MXNetError(
                    f"MXNET_FAULT_SPEC: error={error} is only valid at "
                    f"point=publish, not {point!r}")
        elif error != "KILL" and not hasattr(_errno, error):
            raise MXNetError(f"MXNET_FAULT_SPEC: unknown errno name {error!r}")
        self.point = point
        self.path = path
        self.nth = int(nth)
        self.times = float("inf") if times in ("inf", float("inf")) else int(times)
        self.error = error
        self.truncate = None if truncate is None else int(truncate)
        self.count = 0

    def matches(self, path):
        return (fnmatch.fnmatch(path, self.path) or
                fnmatch.fnmatch(os.path.basename(path), self.path))

    def fire(self, path):
        """Raise (or return self for truncate rules) when this event falls
        in the [nth, nth+times) window of matching events."""
        self.count += 1
        if not (self.nth <= self.count < self.nth + self.times):
            return None
        if self.truncate is not None:
            _logger().warning("fault injection: truncating write of %s at %d bytes",
                              path, self.truncate)
            return self
        if self.error in ("CORRUPT", "STALE"):
            # self-inflicted publish faults: the publisher enacts them on
            # its own output (flip a payload byte / stamp an old version)
            _logger().warning("fault injection: %s publish of %s",
                              self.error, path)
            return self
        if self.error == "KILL":
            raise ThreadKilled(f"fault injection: killed at {self.point} of {path}")
        code = getattr(_errno, self.error)
        raise OSError(code, f"fault injection: {self.error} at {self.point} of {path}")

    def __repr__(self):
        return (f"FaultRule(point={self.point}, path={self.path!r}, "
                f"nth={self.nth}, times={self.times}, error={self.error}, "
                f"truncate={self.truncate})")


def _parse_spec(spec):
    rules = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = {}
        for kv in chunk.split(","):
            key, eq, val = kv.strip().partition("=")
            if not eq:
                raise MXNetError(f"MXNET_FAULT_SPEC: expected key=value, got {kv!r}")
            if key not in ("point", "path", "nth", "times", "error", "truncate"):
                raise MXNetError(f"MXNET_FAULT_SPEC: unknown field {key!r}")
            fields[key] = val
        if "point" not in fields:
            raise MXNetError(f"MXNET_FAULT_SPEC: rule missing point=: {chunk!r}")
        try:
            rules.append(FaultRule(**fields))
        except ValueError as e:  # non-integer nth/times/truncate
            raise MXNetError(f"MXNET_FAULT_SPEC: bad rule {chunk!r}: {e}") from e
    return rules


_fault_lock = threading.Lock()
_fault_spec = None   # env string the compiled rules came from
_fault_rules = []


def _rules():
    """Compiled rules for the CURRENT env value; counters survive as long
    as the spec string is unchanged (re-compiled — and reset — on change)."""
    global _fault_spec, _fault_rules
    spec = os.environ.get("MXNET_FAULT_SPEC", "")
    if spec == _fault_spec:
        return _fault_rules
    with _fault_lock:
        if spec != _fault_spec:
            _fault_rules = _parse_spec(spec)
            _fault_spec = spec
    return _fault_rules


def reset_fault_counters():
    """Restart every rule's event counter (tests reuse one spec)."""
    with _fault_lock:
        for r in _fault_rules:
            r.count = 0


def inject(point, path=""):
    """Fault point hook: no-op unless an active rule matches. Raises the
    rule's OSError / :class:`ThreadKilled`, or returns the rule for
    ``truncate`` rules so the writer can tear its own payload."""
    rules = _rules()
    if not rules:
        return None
    with _fault_lock:
        for rule in rules:
            if rule.point == point and rule.matches(path):
                fired = rule.fire(path)
                if fired is not None:
                    return fired
    return None


class fault_scope:
    """Context manager installing a fault spec (and fresh counters) for a
    test body, restoring the previous spec on exit."""

    def __init__(self, spec):
        self._spec = spec
        self._prev = None

    def __enter__(self):
        self._prev = os.environ.get("MXNET_FAULT_SPEC")
        os.environ["MXNET_FAULT_SPEC"] = self._spec
        try:
            _rules()  # compile now so a bad spec fails at scope entry
        except Exception:
            self.__exit__()  # a rejected spec must not stay in the env
            raise
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            os.environ.pop("MXNET_FAULT_SPEC", None)
        else:
            os.environ["MXNET_FAULT_SPEC"] = self._prev
        _rules()
        return False
