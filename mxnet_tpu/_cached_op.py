"""CachedOp — a python callable captured as ONE compiled XLA program.

Parity: `src/imperative/cached_op.cc` (`CachedOp::Forward` :889 dispatching
to cached graphs keyed by input signature; `SetForwardGraph` :295 signature
match; `CachedOp::Backward` :1160) and the frontend handle
`python/mxnet/_ctypes/ndarray.py:105`.

TPU-native redesign: the reference captures an NNVM graph and replays it
node-by-node through the engine (optionally bulked, `StaticRunOps` :647).
Here capture *is* compilation: the wrapped python function is traced by
`jax.jit` into a single XLA computation — the limit case of engine bulking
(whole-program fusion, static buffer plan by XLA). The signature cache
(shape/dtype of every input, train flag) is jax's jit cache; `static_alloc`/
`static_shape` are accepted for API compatibility and are no-ops because
every CachedOp already gets a static memory plan from XLA.

Autograd: when recording, the forward runs through ``jax.vjp`` (compiled
with the forward) and ONE tape node is recorded whose pullback is the
whole-graph backward — exactly CachedOp::Backward's role.

RNG / train-mode: the compiled program takes a threefry base key as a
traced argument (fresh randomness each call, zero recompiles) and the
train flag is a static cache key — the reference achieves the same with
OpContext::is_train and per-op PRNG resources.
"""
from __future__ import annotations

import jax

from . import autograd
from . import random as _random
from .compile_cache import CompileCache

__all__ = ["CachedOp"]


class CachedOp:
    """Wrap ``fn(*ndarrays) -> NDArray | list[NDArray]`` as a compiled op.

    ``fn`` must be pure python over NDArray ops (the same code the eager
    path runs): it is traced with tracer-backed NDArrays.
    """

    def __init__(self, fn, static_alloc=False, static_shape=False, inline_limit=2):
        self._fn = fn
        self._static_alloc = static_alloc  # accepted for parity; XLA always static-plans
        self._static_shape = static_shape
        self._n_out = None
        # signature-keyed executable cache (the reference's SetForwardGraph
        # :295 signature match) — input shape churn is counted, not silent.
        # Bounded so unbucketed shape churn caps memory, not just visibility
        self._cache = CompileCache("cached_op", maxsize=64)

    # -- tracing ------------------------------------------------------------

    def _traced(self, train):
        """The pure jax function: (key, *arrays) -> tuple of arrays."""
        from .ndarray.ndarray import NDArray

        fn = self._fn

        def run(key, *arrays):
            nds = [NDArray(a) for a in arrays]
            with autograd._RecordingStateScope(False, train):
                with _random.TraceKeyProvider(key):
                    outs = fn(*nds)
            if isinstance(outs, (list, tuple)):
                res = tuple(o._data for o in outs)
                # single output stays a bare leaf so the stored pullback's
                # cotangent convention matches the per-op tape nodes
                return res[0] if len(res) == 1 else res
            return outs._data

        return run

    def _jit_fwd(self, train, sig):
        return self._cache.get_or_build(
            ("fwd", train, sig), lambda: jax.jit(self._traced(train)))

    def _jit_fwd_vjp(self, train, sig):
        def build():
            base = self._traced(train)

            def fwd(key, *arrays):
                outs, vjp = jax.vjp(lambda *a: base(key, *a), *arrays)
                return outs, vjp

            return jax.jit(fwd)

        return self._cache.get_or_build(("fwd_vjp", train, sig), build)

    # -- call ---------------------------------------------------------------

    def __call__(self, *inputs, default_ctx=None):
        from .ndarray.ndarray import NDArray

        arrays = []
        nd_inputs = []
        for a in inputs:
            if isinstance(a, NDArray):
                arrays.append(a._data)
                nd_inputs.append(a)
            else:
                arrays.append(a)
                nd_inputs.append(None)

        train = bool(autograd.is_training())
        recording = autograd.is_recording()
        key = _random.next_key()

        ctx = next((a._ctx for a in nd_inputs if a is not None), default_ctx)
        # hashable dtype objects, not strings — this runs on every call.
        # Non-array inputs key by TYPE only: a python scalar is a traced
        # argument of the shared jit object (weak-typed), so a changing
        # value re-specializes inside jax, never in this cache — keying on
        # the value would compile one executable per distinct scalar
        sig = tuple((a.shape, a.dtype) if hasattr(a, "shape")
                    else (None, type(a).__name__) for a in arrays)

        if recording:
            outs, vjp = self._jit_fwd_vjp(train, sig)(key, *arrays)
            outs_t = outs if isinstance(outs, tuple) else (outs,)
            out_nds = [NDArray(o, ctx) for o in outs_t]
            autograd._record_node(
                vjp, nd_inputs, out_nds,
                [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs_t])
        else:
            outs = self._jit_fwd(train, sig)(key, *arrays)
            outs_t = outs if isinstance(outs, tuple) else (outs,)
            out_nds = [NDArray(o, ctx) for o in outs_t]

        self._n_out = len(out_nds)
        if len(out_nds) == 1:
            return out_nds[0]
        return out_nds
