"""Test utilities (parity: `python/mxnet/test_utils.py`).

The op-correctness harness of the reference test suite:
`assert_almost_equal`:474, `check_numeric_gradient` (central finite
differences over the symbolic executor):801, `check_symbolic_forward`:939 /
`check_symbolic_backward`:1017, `check_consistency` (same graph across
contexts/dtypes):1224, `rand_ndarray`:343, `default_context`:52.

TPU-native notes: gradients under test come from the XLA-compiled vjp of
the whole graph; the finite-difference reference runs the same compiled
forward, so the harness validates the program XLA actually executes, not a
python re-implementation.
"""
from __future__ import annotations

import numpy as np

from . import context as ctx_mod
from . import ndarray as nd
from .base import MXNetError

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "simple_forward", "create_sparse_array"]

_default_ctx = None


def default_context():
    return _default_ctx if _default_ctx is not None else ctx_mod.current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def _as_np(x):
    if isinstance(x, nd.NDArray):
        return x.asnumpy()
    return np.asarray(x)


def find_max_violation(a, b, rtol, atol):
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff - tol
    idx = np.unravel_index(np.argmax(violation), violation.shape)
    return idx, float(diff[idx]), float(np.abs(b)[idx])


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    """Assert |a-b| <= atol + rtol*|b| elementwise (reference :474)."""
    a = _as_np(a)
    b = _as_np(b)
    if a.shape != b.shape:
        raise AssertionError(f"shape mismatch {names[0]}{a.shape} vs "
                             f"{names[1]}{b.shape}")
    if np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    idx, diff, ref = find_max_violation(a, b, rtol, atol)
    raise AssertionError(
        f"values of {names[0]} and {names[1]} differ beyond rtol={rtol} "
        f"atol={atol}: max violation at {idx}: |diff|={diff} vs |{names[1]}|={ref}")


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 scale=1.0):
    """Random NDArray; row_sparse/csr return the sparse wrappers
    (reference :343)."""
    if stype == "default":
        return nd.array(np.random.uniform(-scale, scale, shape).astype(dtype))
    from .ndarray import sparse as _sp

    density = 0.5 if density is None else density
    arr = np.random.uniform(-scale, scale, shape).astype(dtype)
    mask = np.random.rand(*shape) < density
    arr = arr * mask
    if stype == "row_sparse":
        return _sp.RowSparseNDArray.from_dense(nd.array(arr)) \
            if hasattr(_sp.RowSparseNDArray, "from_dense") else \
            _sp.row_sparse_array(arr)
    if stype == "csr":
        return _sp.csr_matrix(arr) if hasattr(_sp, "csr_matrix") else \
            _sp.CSRNDArray(arr)
    raise ValueError(f"unknown stype {stype}")


def create_sparse_array(shape, stype, density=0.5, dtype="float32"):
    return rand_ndarray(shape, stype, density=density, dtype=dtype)


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Bind, feed, run, return numpy outputs (reference simple_forward)."""
    shapes = {k: v.shape for k, v in inputs.items()}
    ex = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
    outputs = ex.forward(is_train=is_train, **inputs)
    outputs = [o.asnumpy() for o in outputs]
    return outputs[0] if len(outputs) == 1 else outputs


def _parse_location(sym, location, dtype="float32"):
    if isinstance(location, dict):
        arg_names = sym.list_arguments()
        for k in location:
            if k not in arg_names:
                raise ValueError(f"{k} not an argument of the symbol "
                                 f"({arg_names})")
        return {k: np.asarray(v.asnumpy() if isinstance(v, nd.NDArray) else v,
                              dtype=dtype)
                for k, v in location.items()}
    return {k: np.asarray(v.asnumpy() if isinstance(v, nd.NDArray) else v,
                          dtype=dtype)
            for k, v in zip(sym.list_arguments(), location)}


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-6,
                           aux_states=None, ctx=None, dtype="float32"):
    """Compare executor outputs against expected numpy arrays
    (reference :939)."""
    location = _parse_location(sym, location, dtype)
    ex = sym.simple_bind(ctx=ctx, grad_req="null",
                         **{k: v.shape for k, v in location.items()})
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k][:] = _as_np(v)
    outputs = ex.forward(is_train=False, **location)
    for out, exp in zip(outputs, expected if isinstance(expected, (list, tuple))
                        else [expected]):
        assert_almost_equal(out.asnumpy(), _as_np(exp), rtol, atol)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-6, aux_states=None, grad_req="write",
                            ctx=None, dtype="float32"):
    """Run backward with given head grads and compare arg grads
    (reference :1017)."""
    location = _parse_location(sym, location, dtype)
    ex = sym.simple_bind(ctx=ctx, grad_req=grad_req,
                         **{k: v.shape for k, v in location.items()})
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k][:] = _as_np(v)
    ex.forward(is_train=True, **location)
    ex.backward([nd.array(_as_np(g)) for g in
                 (out_grads if isinstance(out_grads, (list, tuple))
                  else [out_grads])])
    if isinstance(expected, dict):
        for name, exp in expected.items():
            assert_almost_equal(ex.grad_dict[name].asnumpy(), _as_np(exp),
                                rtol, atol, names=(f"grad({name})", "expected"))
    else:
        for name, exp in zip(sym.list_arguments(), expected):
            if exp is None:
                continue
            assert_almost_equal(ex.grad_dict[name].asnumpy(), _as_np(exp),
                                rtol, atol, names=(f"grad({name})", "expected"))
    return {k: v.asnumpy() for k, v in ex.grad_dict.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None, dtype="float64"):
    """Central finite differences vs the executor's backward (reference :801).

    For every argument in `grad_nodes` (default: all), perturbs each element
    ±eps, re-runs the compiled forward, and compares (f(x+e)-f(x-e))/2e
    against the analytic gradient of sum(outputs) from `backward`.
    """
    location = _parse_location(sym, location, dtype="float64")
    if grad_nodes is None:
        grad_nodes = [k for k in sym.list_arguments() if k in location]

    # analytic grads — run in float32 (ops may hard-cast); FD in float64
    f32_loc = {k: v.astype("float32") for k, v in location.items()}
    ex = sym.simple_bind(ctx=ctx, grad_req={
        k: ("write" if k in grad_nodes else "null")
        for k in sym.list_arguments()},
        **{k: v.shape for k, v in location.items()})
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k][:] = _as_np(v)
    outputs = ex.forward(is_train=use_forward_train, **f32_loc)
    ex.backward([nd.array(np.ones(o.shape, dtype="float32")) for o in outputs])
    analytic = {k: ex.grad_dict[k].asnumpy().astype("float64")
                for k in grad_nodes}

    # numeric: sum of all outputs as the scalar objective
    ex_fd = sym.simple_bind(ctx=ctx, grad_req="null",
                            **{k: v.shape for k, v in location.items()})
    if aux_states:
        for k, v in aux_states.items():
            ex_fd.aux_dict[k][:] = _as_np(v)

    def fval(loc):
        outs = ex_fd.forward(is_train=use_forward_train,
                             **{k: v.astype("float32") for k, v in loc.items()})
        return float(sum(o.asnumpy().astype("float64").sum() for o in outs))

    atol = atol if atol is not None else rtol
    for name in grad_nodes:
        base = location[name]
        numeric = np.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = fval(location)
            flat[i] = orig - numeric_eps
            fm = fval(location)
            flat[i] = orig
            num_flat[i] = (fp - fm) / (2 * numeric_eps)
        assert_almost_equal(analytic[name], numeric, rtol, atol,
                            names=(f"analytic({name})", f"numeric({name})"))
    return analytic


def check_consistency(sym, ctx_list=None, scale=1.0, dtype_list=None,
                      grad_req="write", arg_params=None, rtol=1e-3, atol=1e-4,
                      location=None):
    """Run the same graph under multiple dtypes/contexts and require
    consistent outputs and gradients (reference :1224 — there CPU vs GPU vs
    MKLDNN; here float32 vs float64 vs bfloat16-upcast on the available
    backends, which exercises the same op-lowering surface on TPU/CPU)."""
    dtype_list = dtype_list or ["float64", "float32"]
    arg_names = sym.list_arguments()
    if location is None:
        arg_shapes, _, _ = sym.infer_shape(**(arg_params or {}))
        rng = np.random.RandomState(0)
        location = {n: rng.uniform(-scale, scale, s).astype("float64")
                    for n, s in zip(arg_names, arg_shapes)}

    results = []
    for dtype in dtype_list:
        loc = {k: v.astype(dtype) for k, v in location.items()}
        ex = sym.simple_bind(grad_req=grad_req,
                             **{k: v.shape for k, v in loc.items()})
        outs = ex.forward(is_train=True, **loc)
        ex.backward([nd.array(np.ones(o.shape, dtype="float32"))
                     for o in outs])
        results.append((
            [o.asnumpy().astype("float64") for o in outs],
            {k: v.asnumpy().astype("float64")
             for k, v in ex.grad_dict.items() if v is not None}))

    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(outs, ref_outs):
            assert_almost_equal(a, b, rtol, atol, names=("out", "ref_out"))
        for k in grads:
            assert_almost_equal(grads[k], ref_grads[k], rtol, atol,
                                names=(f"grad({k})", f"ref_grad({k})"))
    return results
