"""Device-memory accounting: a live buffer census by category.

The framework makes memory CLAIMS — ZeRO-1 allocates optimizer state at
1/N bytes per replica (`parallel/zero1.py`), serving pins one padded batch
buffer set per bucket, the fused step donates weights so no second copy
exists — and before this module nothing in a live process could verify
them. This module is the truth plane:

* **categories** — every long-lived device buffer the framework owns is
  registered under one of ``weights`` / ``optimizer_state`` /
  ``gradients`` / ``serving_batches`` / ``kv_cache`` (the generation
  engines' preallocated KV slabs — registered as live-view providers
  because the slab arrays are REPLACED by every donated decode step;
  prefix-cache entries and their forked session copies are ROWS of that
  same slab, so the buffer-pointer dedup below attributes them once, at
  the slab's allocation size, never double — only a speculative draft
  model's own slab adds bytes, through its own provider);
  everything else live on the backend (feeds in flight, temporaries the
  GC has not collected) shows up as ``other``. Registration is by WEAK reference — a provider
  (executor, updater, ZeRO-1 context, predictor) that dies drops out of
  the census automatically, and tracking never extends a buffer's
  lifetime.
* **census** — :func:`census` walks the live registrations, reads each
  buffer's *physical* per-device residency (``addressable_shards`` — a
  dp-sharded ZeRO-1 state bucket counts 1/N per device, a replicated
  weight counts fully on every device) and publishes ``memory.*``
  gauges: per category, ``memory.<cat>_bytes`` is the max bytes any one
  device holds (the HBM-pressure number) and ``memory.<cat>_bytes_total``
  the sum across local devices.
* **per-executable peak HBM** — :meth:`CompileCache.entry_memory
  <mxnet_tpu.compile_cache.CompileCache.entry_memory>` feeds
  :func:`executable_stats`: XLA's compiled-program memory analysis
  (argument/output/temp bytes) per cache entry, so "which program's
  working set blew the HBM budget" is answerable per compiled executable.

Census cost is O(live buffers) with device reads only on shard metadata —
it runs on demand (telemetry HTTP ``/memory``, ``prom_text()``, tests),
never on the step path.
"""
from __future__ import annotations

import threading
import weakref

from . import analysis
from . import telemetry
from .base import getenv, register_env

__all__ = ["CATEGORIES", "track", "track_transient", "register_provider",
           "census", "update_gauges", "executable_stats", "clear",
           "device_capacity_bytes"]

register_env("MXNET_DEVICE_HBM_BYTES", 0,
             "per-device memory capacity override in bytes for the "
             "memory.headroom_bytes gauge; 0 = use the backend's "
             "reported bytes_limit (none on CPU: headroom unpublished)")

CATEGORIES = ("weights", "optimizer_state", "gradients", "serving_batches",
              "kv_cache")

_lock = analysis.make_lock("memory.census")
# category -> list of weakref.ref to NDArray / jax array (long-lived)
_tracked = {c: [] for c in CATEGORIES}
# category -> list of (weakref to owner, getter(owner) -> iterable of arrays)
_providers = {c: [] for c in CATEGORIES}
_SWEEP_FLOOR = 4096
# category -> list length that triggers the next inline dead-ref sweep.
# Doubles past the live count after a sweep that freed little, so a
# category that legitimately holds >4096 LIVE buffers pays O(n) per
# geometric growth step, not per track() call
_sweep_at = {c: _SWEEP_FLOOR for c in CATEGORIES}


def clear():
    """Drop every registration (tests)."""
    with _lock:
        for c in CATEGORIES:
            _tracked[c] = []
            _providers[c] = []
            _sweep_at[c] = _SWEEP_FLOOR


def track(category, arrays):
    """Register long-lived buffers under ``category`` (NDArray, jax array,
    or an iterable of either). Weakly referenced — dead entries are swept
    at census time."""
    if category not in _tracked:
        raise ValueError(f"unknown memory category {category!r} "
                         f"(one of {CATEGORIES})")
    if not isinstance(arrays, (list, tuple, set)):
        arrays = [arrays]
    refs = []
    for a in arrays:
        try:
            refs.append(weakref.ref(a))
        except TypeError:
            pass  # unweakrefable leaf (python scalar riding a state tuple)
    with _lock:
        cur = _tracked[category]
        cur.extend(refs)
        if len(cur) > _sweep_at[category]:
            # bound the list between censuses: drop dead refs inline so a
            # long serving run that never scrapes /memory stays O(live)
            kept = [r for r in cur if r() is not None]
            _tracked[category] = kept
            _sweep_at[category] = max(_SWEEP_FLOOR, 2 * len(kept))


# transient buffers (a serving batch in flight) use the same list — the
# weakref dies with the buffer, and the periodic sweep keeps the list
# bounded. The distinct name keeps call sites honest about lifetime.
track_transient = track


def register_provider(category, owner, getter):
    """Register a LIVE view: ``getter(owner)`` is called at census time to
    enumerate the category's current buffers (for state that is replaced
    every step, e.g. ZeRO-1's donated flat state arrays — a snapshot
    weakref would die on the first update). ``owner`` is weakly held."""
    if category not in _providers:
        raise ValueError(f"unknown memory category {category!r} "
                         f"(one of {CATEGORIES})")
    with _lock:
        _providers[category].append((weakref.ref(owner), getter))


def _unwrap(obj):
    """NDArray -> its jax buffer; jax arrays pass through."""
    data = getattr(obj, "_data", None)
    return data if data is not None else obj


def _per_device_nbytes(arr):
    """{device_key: physical bytes} for one buffer. Sharded arrays report
    each shard on its device (the 1/N truth); replicated-on-mesh arrays
    report the full size on EVERY device they occupy."""
    try:
        shards = arr.addressable_shards
    except Exception:  # noqa: BLE001 — not a jax array (numpy fallback)
        nb = int(getattr(arr, "nbytes", 0))
        return {"host": nb} if nb else {}
    out = {}
    for s in shards:
        out[str(s.device)] = out.get(str(s.device), 0) + int(s.data.nbytes)
    return out


def _buffer_key(arr):
    """Identity for dedup: two NDArrays sharing one jax buffer (shared
    serving weights bound into several bucket executors) count once."""
    try:
        return arr.unsafe_buffer_pointer()
    except Exception:  # noqa: BLE001
        return id(arr)


def _iter_category(category):
    """Live buffers of one category: swept tracked refs + provider views.

    The dead-ref sweeps run entirely under ``_lock`` — dereferencing a
    weakref is cheap and census is off the step path, and holding the
    lock means a concurrent :func:`track` (which may REPLACE the list
    when the 4096 bound trips) can never interleave with the sweep's
    rewrite. Only the provider ``getter`` calls (arbitrary user code)
    run outside the lock."""
    live = []
    with _lock:
        cur = _tracked[category]
        kept = []
        for r in cur:
            o = r()
            if o is not None:
                live.append(o)
                kept.append(r)
        if len(kept) != len(cur):
            _tracked[category] = kept
            _sweep_at[category] = max(_SWEEP_FLOOR, 2 * len(kept))
        cur_p = _providers[category]
        kept_p = [(ref, getter) for ref, getter in cur_p
                  if ref() is not None]
        if len(kept_p) != len(cur_p):
            _providers[category] = kept_p
    for ref, getter in kept_p:
        owner = ref()
        if owner is None:  # died since the sweep
            continue
        try:
            live.extend(getter(owner) or [])
        except Exception:  # noqa: BLE001 — a dying provider must not kill
            pass           # the census
    return live


def census(update=True):
    """One coherent memory snapshot::

        {"categories": {cat: {"total", "per_device_max", "buffers"}},
         "per_device": {device: bytes (categorized)},
         "live_total": <all live backend arrays>,
         "other": live_total - categorized,
         "device_count": N}

    ``update=True`` (default) also publishes the ``memory.*`` gauges so
    the next telemetry snapshot / ``prom_text()`` carries them."""
    seen = set()
    cats = {}
    per_device = {}
    categorized = 0
    for cat in CATEGORIES:
        total = 0
        dev = {}
        n = 0
        for obj in _iter_category(cat):
            arr = _unwrap(obj)
            if arr is None:
                continue
            key = _buffer_key(arr)
            if key in seen:
                continue
            seen.add(key)
            by_dev = _per_device_nbytes(arr)
            if not by_dev:
                continue
            n += 1
            for d, nb in by_dev.items():
                dev[d] = dev.get(d, 0) + nb
                per_device[d] = per_device.get(d, 0) + nb
                total += nb
        categorized += total
        cats[cat] = {"total": total,
                     "per_device_max": max(dev.values()) if dev else 0,
                     "buffers": n}
    live_total = 0
    try:
        import jax

        live_seen = set()
        for a in jax.live_arrays():
            k = _buffer_key(a)
            if k in live_seen:
                continue
            live_seen.add(k)
            live_total += sum(_per_device_nbytes(a).values())
    except Exception:  # noqa: BLE001 — census must degrade, not raise
        live_total = categorized
    out = {"categories": cats,
           "per_device": per_device,
           "live_total": live_total,
           "other": max(0, live_total - categorized),
           "device_count": len(per_device)}
    cap = device_capacity_bytes()
    if cap:
        # peak-HBM headroom PROJECTED to the worst already-analyzed
        # executable: capacity − (busiest device's categorized bytes +
        # unattributed live bytes + the largest temp working set any
        # warmed program needs while it runs). Negative means the next
        # dispatch of that program is an OOM waiting to happen even
        # though the resident census still fits — the SLO default row
        # memory.headroom_bytes:value>=0 burns on exactly that.
        used = max(per_device.values()) if per_device else 0
        out["capacity_bytes"] = cap
        out["worst_executable_temp_bytes"] = _worst_temp_bytes()
        out["headroom_bytes"] = (cap - used - out["other"]
                                 - out["worst_executable_temp_bytes"])
    if update:
        _publish(out)
    return out


def device_capacity_bytes():
    """Per-device memory capacity in bytes: the backend's reported
    ``bytes_limit`` where available (TPU/GPU), else the
    ``MXNET_DEVICE_HBM_BYTES`` override, else 0 (unknown — headroom is
    not published)."""
    cap = int(getenv("MXNET_DEVICE_HBM_BYTES"))
    if cap:
        return cap
    try:
        import jax

        ms = jax.devices()[0].memory_stats()
        if ms:
            return int(ms.get("bytes_limit") or 0)
    except Exception:  # noqa: BLE001 — CPU backends have no stats
        pass
    return 0


def _worst_temp_bytes():
    """Largest temp working set among executables whose lazy memory
    analysis has ALREADY run (compute=False — the census never triggers
    an AOT pass; /memory's executable_stats(compute=True) is what fills
    this in)."""
    from . import compile_cache

    worst = 0
    for c in compile_cache.all_caches():
        for row in c.memory_stats(compute=False):
            worst = max(worst, int(row.get("temp_bytes") or 0))
    return worst


def _publish(snap):
    """The gauges. Unconditional (like compile.* counters): memory truth
    must be visible even when the wider telemetry plane is off."""
    for cat, v in snap["categories"].items():
        telemetry.gauge(f"memory.{cat}_bytes").set(v["per_device_max"])
        telemetry.gauge(f"memory.{cat}_bytes_total").set(v["total"])
    telemetry.gauge("memory.other_bytes").set(snap["other"])
    telemetry.gauge("memory.live_bytes_total").set(snap["live_total"])
    if "headroom_bytes" in snap:
        telemetry.gauge("memory.headroom_bytes").set(snap["headroom_bytes"])
        telemetry.gauge("memory.capacity_bytes").set(snap["capacity_bytes"])


def update_gauges():
    """Refresh ``memory.*`` gauges from a fresh census (prom_text / the
    HTTP endpoint call this right before rendering)."""
    return census(update=True)


def executable_stats():
    """Per-executable peak-HBM from XLA's compiled-program memory
    analysis, for every :class:`~mxnet_tpu.compile_cache.CompileCache`
    entry: ``{cache_name: [{key, argument_bytes, output_bytes, temp_bytes,
    peak_bytes}]}``. Lazy and memoized per entry, never on the step path —
    but the FIRST call after new compiles pays an AOT lowering pass per
    new entry, which for donated (persistent=False) programs is a full
    recompile: expect the first ``/memory`` scrape of a freshly-warmed
    process to take seconds."""
    from . import compile_cache

    out = {}
    for c in compile_cache.all_caches():
        # compute=True: this is the on-demand read — without it the lazy
        # analysis would never run anywhere. Memoized per entry (failures
        # too), so repeat scrapes pay nothing
        rows = c.memory_stats(compute=True)
        if rows:
            out.setdefault(c.name, []).extend(rows)
    return out
