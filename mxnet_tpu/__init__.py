"""mxnet_tpu — a TPU-native deep-learning framework with MXNet-1.5
capabilities (`import mxnet_tpu as mx` is the intended spelling).

Re-designed from scratch for TPU (see SURVEY.md at the repo root): compute
lowers to XLA through jax, captured graphs compile to cached executables,
device placement is GSPMD sharding, and distributed sync is XLA collectives
over ICI/DCN. API parity follows the reference `python/mxnet/__init__.py`.
"""

__version__ = "0.1.0"

# `tools/launch.py` workers force their jax platform via MXNET_DIST_PLATFORM.
# It must be applied before ANY backend touch (an NDArray built before
# kv.create would otherwise initialise the default — possibly TPU — backend
# and the later update would be a no-op with N workers fighting for one chip).
import os as _os

if _os.environ.get("MXNET_DIST_PLATFORM"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["MXNET_DIST_PLATFORM"])
    # gloo cross-process collectives need a jax.distributed client; only a
    # launcher-spawned worker (rendezvous env present — our launcher's
    # coordinator vars, DMLC, or mpirun's OMPI vars, exactly the branches
    # launcher.initialize_from_env accepts) has one — a single-process run
    # with the flag set cannot even init the backend
    if _os.environ["MXNET_DIST_PLATFORM"] == "cpu" and (
            _os.environ.get("MXNET_COORDINATOR")
            or _os.environ.get("DMLC_PS_ROOT_URI")
            or _os.environ.get("OMPI_COMM_WORLD_SIZE")):
        _jax.config.update("jax_cpu_collectives_implementation", "gloo")

from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus

from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray

from . import symbol
from . import symbol as sym
from .symbol import Symbol

from . import io
from . import image
from . import module
from . import module as mod

from . import autograd
from . import random
from .random import seed

from . import engine
from . import lazy
from . import resilience
from . import telemetry
from . import tracing
from . import memory
from . import health
from . import compile_cache
from . import runtime

from . import initializer
from . import initializer as init
from . import lr_scheduler
from . import optimizer
from . import kvstore as kv
from . import kvstore
from . import model
from . import serving
from . import recordio
from . import rnn
from . import test_utils
from . import gluon

from . import metric
from . import callback
from . import monitor
from . import profiler
from . import util
from . import visualization
from . import visualization as viz
from . import image as img
from . import contrib
from . import attribute
from . import registry
from . import rtc
from . import log
from . import kvstore_server
from . import operator  # Custom op itself registers in ops/__init__
from .attribute import AttrScope
from . import name
from .name import NameManager
