"""KVStore server bootstrap (parity: `python/mxnet/kvstore_server.py` —
the reference starts a `KVStoreServer` applying pickled optimizers when
launched with DMLC_ROLE=server).

DOCUMENTED DIVERGENCE: the TPU build has no parameter servers — gradient
synchronization is synchronous XLA AllReduce over ICI/DCN inside the SPMD
program (`mxnet_tpu/parallel/dist.py`), the role the reference's server
processes played (`kvstore_dist_server.h:155`, SURVEY.md §5). This module
keeps the import surface and explains the mapping; launching with a
server/scheduler role is an explicit error pointing at tools/launch.py.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """API-parity shim of the reference server controller. `run()` refuses
    with the TPU mapping instead of blocking in a ZMQ loop."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore

    def run(self):
        raise MXNetError(
            "Parameter-server processes do not exist on TPU: every worker "
            "participates in synchronous AllReduce collectives instead "
            "(kvstore 'dist_tpu_sync'; launch workers with tools/launch.py)."
        )


def _init_kvstore_server_module():
    """Reference `kvstore_server.py:_init_kvstore_server_module`: when the
    process is launched in a server/scheduler role, take over as a server.
    Here those roles are an error (no servers to become)."""
    role = os.environ.get("DMLC_ROLE", "")
    if role in ("server", "scheduler"):
        raise MXNetError(
            f"DMLC_ROLE={role!r}: the TPU build has no {role} role — "
            "dist_tpu_sync replaces ps-lite with XLA collectives; launch "
            "N workers via tools/launch.py (jax.distributed rendezvous).")


_init_kvstore_server_module()
