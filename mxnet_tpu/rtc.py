"""Runtime kernel compilation (parity: `python/mxnet/rtc.py` CudaModule
over `include/mxnet/rtc.h:39` NVRTC).

TPU-native replacement: there is no NVRTC; runtime kernel compilation on
TPU is jax.jit (XLA) and Pallas (`jax.experimental.pallas`) — see
`mxnet_tpu/gradient_compression.py` `quantize_2bit_pallas` for the
in-tree example. `XlaModule` offers the CudaModule-shaped API over a
python kernel function; `CudaModule` itself raises with that pointer
(documented divergence)."""
from __future__ import annotations

import jax

from .base import MXNetError

__all__ = ["CudaModule", "XlaModule"]


class CudaModule:
    """Unsupported on TPU (reference rtc.py compiled CUDA source at
    runtime). Use :class:`XlaModule` / Pallas instead."""

    def __init__(self, *a, **kw):
        raise MXNetError(
            "CudaModule (NVRTC) does not exist on TPU. Write the kernel as "
            "a jax/Pallas function and wrap it with mxnet_tpu.rtc.XlaModule "
            "(runtime compilation is XLA's job here).")


class _Kernel:
    def __init__(self, jitted, name):
        self._fn = jitted
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """CudaModule-shaped launch: ctx/grid/block/shared_mem are accepted
        and IGNORED (XLA owns device placement and scheduling); returns the
        kernel outputs as NDArrays."""
        from .ndarray import NDArray

        arrays = [a._data if isinstance(a, NDArray) else a for a in args]
        out = self._fn(*arrays)
        if isinstance(out, (list, tuple)):
            return [NDArray(o) for o in out]
        return NDArray(out)


class XlaModule:
    """Runtime-compiled kernel collection: pass python functions over jax
    arrays; each gets a jitted, launchable handle (the CudaModule
    get_kernel shape without signature strings — types come from tracing).
    Kernels jit ONCE at module construction; repeated get_kernel of the
    same name returns the same compiled handle."""

    def __init__(self, **kernels):
        self._kernels = {name: _Kernel(jax.jit(fn), name)
                         for name, fn in kernels.items()}

    def get_kernel(self, name, signature=None):
        if name not in self._kernels:
            raise MXNetError(f"kernel {name!r} not in module; have "
                             f"{sorted(self._kernels)}")
        return self._kernels[name]
