"""Legacy rnn package (parity: `python/mxnet/rnn/`): BucketSentenceIter +
cell aliases. The gluon cells are the maintained implementation; the legacy
symbolic cell classes re-export them for API parity."""
from .io import BucketSentenceIter, encode_sentences
from ..gluon.rnn import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                         BidirectionalCell, DropoutCell, ZoneoutCell,
                         ResidualCell)

__all__ = ["BucketSentenceIter", "encode_sentences", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell"]
