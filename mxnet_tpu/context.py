"""Device contexts.

Parity with the reference's `python/mxnet/context.py` (`Context`, `cpu()`,
`gpu()`, thread-local default-context stack) redesigned for TPU: a Context
names a jax device. ``gpu(i)`` is kept as an alias for accelerator ``i`` so
reference scripts run unchanged; the native accelerator constructor is
``tpu(i)``. `Context.device_typeid` numbering keeps the reference's values
(cpu=1, gpu=2, cpu_pinned=3, cpu_shared=5) plus tpu=6 so serialized contexts
round-trip.
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]

_devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
_devstr2type = {v: k for k, v in _devtype2str.items()}


def _jax():
    import jax

    return jax


class Context:
    """A device context. ``with mx.tpu(0):`` sets the default device for
    array creation, mirroring `python/mxnet/context.py:39`."""

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = _devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return _devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- TPU-native part ----------------------------------------------------

    @property
    def jax_device(self):
        """The concrete jax device this context names."""
        jax = _jax()
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _platform_devices("cpu")
            if not devs:
                devs = jax.devices()  # single-platform builds
            return devs[min(self.device_id, len(devs) - 1)]
        devs = _accelerator_devices()
        if not devs:
            devs = _platform_devices("cpu")
        if self.device_id >= len(devs):
            raise ValueError(f"{self} does not name an available device ({len(devs)} present)")
        return devs[self.device_id]

    def empty_cache(self):
        """Parity no-op: XLA owns the HBM allocator."""


def _platform_devices(platform):
    """Addressable devices of a platform. A Context names a device THIS
    process can touch — in a multi-process job `jax.devices()` includes
    other workers' (non-addressable) devices, which eager ops must never
    device_put to (reference contexts are per-process for the same reason)."""
    jax = _jax()
    try:
        return [d for d in jax.local_devices() if d.platform == platform]
    except RuntimeError:
        return []


def _accelerator_devices():
    """All non-cpu addressable jax devices (tpu; 'axon' tunnel; gpu)."""
    jax = _jax()
    return [d for d in jax.local_devices() if d.platform != "cpu"]


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias for the i-th accelerator so reference scripts run unchanged."""
    return Context("gpu", device_id)


def num_tpus():
    return len(_accelerator_devices())


def num_gpus():
    return num_tpus()


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


def default_accelerator():
    """tpu(0) if an accelerator is present else cpu(0)."""
    return tpu(0) if num_tpus() > 0 else cpu(0)
