"""Sparse NDArrays: row_sparse + csr.

Parity: `python/mxnet/ndarray/sparse.py` (RowSparseNDArray, CSRNDArray,
zeros/array/cast_storage) over the reference's storage types
(`include/mxnet/ndarray.h:61-66`) and sparse kernels
(`src/operator/tensor/cast_storage-inl.h`, `dot.cc`, `sparse_retain.cc`,
`square_sum.cc`).

TPU-native design: XLA has no native sparse buffers, so compound storage is
kept as (data, indices[, indptr]) dense components — exactly the
reference's aux-data layout — and sparse ops lower to XLA gather/scatter
(take / segment_sum). Ops that have no sparse win fall back to dense, the
analogue of the reference's storage-fallback executor
(`attach_op_execs_pass.cc:46`).
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp

from .ndarray import NDArray, array as _dense_array, zeros as _dense_zeros
from ..base import MXNetError, np_dtype

__all__ = ["RowSparseNDArray", "CSRNDArray", "zeros", "array", "row_sparse_array",
           "csr_matrix", "cast_storage", "retain", "dot"]


class BaseSparseNDArray(NDArray):
    __slots__ = ("_aux",)


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: (data[K, ...], indices[K]) — K occupied rows of a
    logically dense (N, ...) array."""

    def __init__(self, data, indices, shape, ctx=None):
        dense = jnp.zeros(shape, data._data.dtype if isinstance(data, NDArray) else data.dtype)
        self._aux = {
            "data": data if isinstance(data, NDArray) else NDArray(jnp.asarray(data)),
            "indices": indices if isinstance(indices, NDArray) else NDArray(jnp.asarray(indices)),
        }
        full = dense.at[self._aux["indices"]._data.astype(jnp.int32)].set(self._aux["data"]._data) \
            if self._aux["indices"].size else dense
        super().__init__(full, ctx, stype="row_sparse")

    @property
    def data(self):
        return self._aux["data"]

    @property
    def indices(self):
        return self._aux["indices"]

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cast_storage from row_sparse to {stype} not supported")

    def __repr__(self):
        return f"\n<RowSparseNDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(), self.shape, self._ctx)

    def retain(self, indices):
        return retain(self, indices)


class CSRNDArray(BaseSparseNDArray):
    """csr: (data[nnz], indices[nnz], indptr[N+1]) 2-D sparse matrix."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        self._aux = {
            "data": data if isinstance(data, NDArray) else NDArray(jnp.asarray(data)),
            "indices": indices if isinstance(indices, NDArray) else NDArray(jnp.asarray(indices)),
            "indptr": indptr if isinstance(indptr, NDArray) else NDArray(jnp.asarray(indptr)),
        }
        d = self._aux["data"]._data
        idx = self._aux["indices"]._data.astype(jnp.int32)
        ptr = _np.asarray(self._aux["indptr"]._data)
        dense = _np.zeros(shape, dtype=_np.asarray(d).dtype)
        dnp = _np.asarray(d)
        inp = _np.asarray(idx)
        for r in range(shape[0]):
            for j in range(int(ptr[r]), int(ptr[r + 1])):
                dense[r, inp[j]] = dnp[j]
        super().__init__(jnp.asarray(dense), ctx, stype="csr")

    @property
    def data(self):
        return self._aux["data"]

    @property
    def indices(self):
        return self._aux["indices"]

    @property
    def indptr(self):
        return self._aux["indptr"]

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cast_storage from csr to {stype} not supported")

    def __repr__(self):
        return f"\n<CSRNDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2 and not isinstance(arg1[0], int):
        data, indices = arg1
        return RowSparseNDArray(_dense_array(data, dtype=dtype), _dense_array(indices, dtype="int64"),
                                shape, ctx)
    # dense input → convert
    dense = _dense_array(arg1, ctx=ctx, dtype=dtype) if not isinstance(arg1, NDArray) else arg1
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(_dense_array(data, dtype=dtype), _dense_array(indices, dtype="int64"),
                          _dense_array(indptr, dtype="int64"), shape, ctx)
    dense = _dense_array(arg1, ctx=ctx, dtype=dtype) if not isinstance(arg1, NDArray) else arg1
    return cast_storage(dense, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    dt = np_dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(NDArray(jnp.zeros((0,) + tuple(shape[1:]), dt)),
                                NDArray(jnp.zeros((0,), jnp.int64)), tuple(shape), ctx)
    if stype == "csr":
        return CSRNDArray(NDArray(jnp.zeros((0,), dt)), NDArray(jnp.zeros((0,), jnp.int64)),
                          NDArray(jnp.zeros((shape[0] + 1,), jnp.int64)), tuple(shape), ctx)
    return _dense_zeros(shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, BaseSparseNDArray):
        return source_array
    return _dense_array(source_array, ctx=ctx, dtype=dtype)


def cast_storage(arr, stype):
    """Parity: `cast_storage` op (`src/operator/tensor/cast_storage.cc`)."""
    npv = arr.asnumpy()
    if stype == "row_sparse":
        nz_rows = _np.where(_np.any(npv.reshape(npv.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(
            _dense_array(npv[nz_rows], dtype=npv.dtype),
            _dense_array(nz_rows.astype(_np.int64), dtype="int64"),
            npv.shape, arr._ctx,
        )
    if stype == "csr":
        try:
            import scipy.sparse as sp

            m = sp.csr_matrix(npv)
            return CSRNDArray(_dense_array(m.data, dtype=npv.dtype),
                              _dense_array(m.indices.astype(_np.int64), dtype="int64"),
                              _dense_array(m.indptr.astype(_np.int64), dtype="int64"),
                              npv.shape, arr._ctx)
        except ImportError:
            data, indices, indptr = [], [], [0]
            for r in range(npv.shape[0]):
                cols = _np.where(npv[r] != 0)[0]
                data.extend(npv[r, cols].tolist())
                indices.extend(cols.tolist())
                indptr.append(len(indices))
            return CSRNDArray(_dense_array(_np.asarray(data, npv.dtype)),
                              _dense_array(_np.asarray(indices, _np.int64), dtype="int64"),
                              _dense_array(_np.asarray(indptr, _np.int64), dtype="int64"),
                              npv.shape, arr._ctx)
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    raise MXNetError(f"unknown stype {stype}")


def retain(arr, indices):
    """sparse_retain (`src/operator/tensor/sparse_retain.cc`)."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    idx = indices.asnumpy().astype(_np.int64) if isinstance(indices, NDArray) else _np.asarray(indices, _np.int64)
    keep = _np.isin(arr.indices.asnumpy(), idx)
    return RowSparseNDArray(
        _dense_array(arr.data.asnumpy()[keep]),
        _dense_array(arr.indices.asnumpy()[keep], dtype="int64"),
        arr.shape, arr._ctx,
    )


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """csr × dense / row_sparse-aware dot — lowers to dense XLA dot (the
    gather-based path is a later optimization)."""
    from . import invoke_nd

    return invoke_nd("dot", NDArray(lhs._data, lhs._ctx), NDArray(rhs._data, rhs._ctx),
                     transpose_a=transpose_a, transpose_b=transpose_b)
