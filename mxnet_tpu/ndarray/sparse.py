"""Sparse NDArrays: row_sparse + csr.

Parity: `python/mxnet/ndarray/sparse.py` (RowSparseNDArray, CSRNDArray,
zeros/array/cast_storage) over the reference's storage types
(`include/mxnet/ndarray.h:61-66`) and sparse kernels
(`src/operator/tensor/cast_storage-inl.h`, `dot.cc`, `sparse_retain.cc`,
`square_sum.cc`).

TPU-native design: XLA has no native sparse buffers, so compound storage is
the (data, indices[, indptr]) dense components — exactly the reference's
aux-data layout — and sparse ops lower to XLA gather/segment_sum. The
logically-dense view is **lazy**: nothing materializes the full array until
a dense-only code path reads `_data` (the storage-fallback rule of
`attach_op_execs_pass.cc:46`); `shape`/`dtype`/`size` come from metadata,
so a 1M-row row_sparse gradient flows through retain/optimizer-update
without ever allocating the dense matrix.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from .ndarray import NDArray, array as _dense_array, zeros as _dense_zeros
from ..base import MXNetError, np_dtype

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray", "zeros",
           "array", "row_sparse_array", "csr_matrix", "cast_storage",
           "retain", "dot", "square_sum", "add"]


def _as_nd(x, dtype=None):
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x, dtype))


class BaseSparseNDArray(NDArray):
    """Compound-storage NDArray. `_data` (the dense view) is a lazily
    computed property; sparse components live in `_aux`."""

    __slots__ = ("_aux", "_shape_meta", "_dtype_meta", "_dense_cache",
                 "_aux_stale")

    def __init__(self, aux, shape, dtype, ctx, stype):
        # NDArray slots, minus _data (shadowed by the property below)
        self._aux = aux
        self._shape_meta = tuple(int(s) for s in shape)
        self._dtype_meta = _np.dtype(dtype)
        self._dense_cache = None
        self._aux_stale = False
        self._ctx = ctx
        self.grad = None
        self.grad_req = "null"
        self._ag_marked = False
        self._stype = stype
        self._fresh_grad = False

    # -- lazy dense view -----------------------------------------------------

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._to_dense()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        # a dense value was written into this array (fallback path); aux
        # components re-sparsify lazily on next access
        self._dense_cache = value
        self._shape_meta = tuple(int(s) for s in value.shape)
        self._aux_stale = True

    @property
    def _buf(self):
        # sparse arrays are never lazy: the raw-buffer view IS the dense
        # view (NDArray methods like detach read _buf to avoid flushing)
        return self._data

    @_buf.setter
    def _buf(self, value):
        self._data = value

    def _components(self):
        if self._aux_stale:
            self._resparsify(self._dense_cache)
            self._aux_stale = False
        return self._aux

    @property
    def shape(self):
        return self._shape_meta

    @property
    def dtype(self):
        return self._dtype_meta

    @property
    def ndim(self):
        return len(self._shape_meta)

    @property
    def size(self):
        return int(_np.prod(self._shape_meta)) if self._shape_meta else 0

    def densified(self):
        """True if the dense view has been materialized (test hook)."""
        return self._dense_cache is not None

    def _to_dense(self):
        raise NotImplementedError

    def _resparsify(self, dense):
        raise NotImplementedError


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: (data[K, ...], indices[K]) — K occupied rows of a
    logically dense (N, ...) array. Indices are sorted unique."""

    def __init__(self, data, indices, shape, ctx=None):
        data = _as_nd(data)
        indices = _as_nd(indices, jnp.int32)
        super().__init__({"data": data, "indices": indices}, shape,
                         data.dtype, ctx, "row_sparse")

    @property
    def data(self):
        return self._components()["data"]

    @property
    def indices(self):
        return self._components()["indices"]

    def _to_dense(self):
        aux = self._components()
        dense = jnp.zeros(self._shape_meta, self._dtype_meta)
        if aux["indices"].size:
            dense = dense.at[aux["indices"]._data.astype(jnp.int32)].set(
                aux["data"]._data)
        return dense

    def _resparsify(self, dense):
        nz = jnp.any((dense != 0).reshape(dense.shape[0], -1), axis=1)
        idx = jnp.nonzero(nz)[0]
        self._aux = {"data": NDArray(jnp.take(dense, idx, axis=0)),
                     "indices": NDArray(idx.astype(jnp.int32))}

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cast_storage from row_sparse to {stype} not supported")

    def __setitem__(self, key, value):
        # `g[:] = 0` (Parameter.zero_grad) must stay O(rows): reset the
        # sparse components instead of materializing a dense zeros(table)
        if isinstance(key, slice) and key == slice(None) and \
                _np.isscalar(value) and value == 0:
            self._aux = {"data": NDArray(jnp.zeros((0,) + self._shape_meta[1:],
                                                   self._dtype_meta)),
                         "indices": NDArray(jnp.zeros((0,), jnp.int32))}
            self._dense_cache = None
            self._aux_stale = False
            return
        super().__setitem__(key, value)

    def astype(self, dtype, copy=True):
        """Stays row_sparse (the reference's Cast keeps storage type)."""
        return RowSparseNDArray(self.data.astype(dtype), self.indices.copy(),
                                self.shape, self._ctx)

    def __repr__(self):
        return f"\n<RowSparseNDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self.shape, self._ctx)

    def retain(self, indices):
        return retain(self, indices)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return add(self, other)
        return super().__add__(other)


class CSRNDArray(BaseSparseNDArray):
    """csr: (data[nnz], indices[nnz], indptr[N+1]) 2-D sparse matrix."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        data = _as_nd(data)
        indices = _as_nd(indices, jnp.int32)
        indptr = _as_nd(indptr, jnp.int32)
        super().__init__({"data": data, "indices": indices, "indptr": indptr},
                         shape, data.dtype, ctx, "csr")

    @property
    def data(self):
        return self._components()["data"]

    @property
    def indices(self):
        return self._components()["indices"]

    @property
    def indptr(self):
        return self._components()["indptr"]

    def _row_ids(self):
        """Per-nnz row id from indptr — vectorized (searchsorted)."""
        aux = self._components()
        nnz = int(aux["data"].size)
        ptr = aux["indptr"]._data
        return jnp.searchsorted(ptr, jnp.arange(nnz), side="right") - 1

    def _to_dense(self):
        aux = self._components()
        dense = jnp.zeros(self._shape_meta, self._dtype_meta)
        if aux["data"].size:
            rows = self._row_ids().astype(jnp.int32)
            cols = aux["indices"]._data.astype(jnp.int32)
            dense = dense.at[rows, cols].set(aux["data"]._data)
        return dense

    def _resparsify(self, dense):
        d = _np.asarray(dense)
        rows, cols = _np.nonzero(d)
        order = _np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        indptr = _np.zeros(d.shape[0] + 1, _np.int64)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr)
        self._aux = {"data": NDArray(jnp.asarray(d[rows, cols])),
                     "indices": NDArray(jnp.asarray(cols.astype(_np.int64))),
                     "indptr": NDArray(jnp.asarray(indptr))}

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cast_storage from csr to {stype} not supported")

    def __repr__(self):
        return f"\n<CSRNDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2 and not isinstance(arg1[0], int):
        data, indices = arg1
        return RowSparseNDArray(_dense_array(data, dtype=dtype),
                                _dense_array(indices, dtype="int32"),
                                shape, ctx)
    dense = _dense_array(arg1, ctx=ctx, dtype=dtype) \
        if not isinstance(arg1, NDArray) else arg1
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(_dense_array(data, dtype=dtype),
                          _dense_array(indices, dtype="int32"),
                          _dense_array(indptr, dtype="int32"), shape, ctx)
    dense = _dense_array(arg1, ctx=ctx, dtype=dtype) \
        if not isinstance(arg1, NDArray) else arg1
    return cast_storage(dense, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    dt = np_dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(NDArray(jnp.zeros((0,) + tuple(shape[1:]), dt)),
                                NDArray(jnp.zeros((0,), jnp.int32)),
                                tuple(shape), ctx)
    if stype == "csr":
        return CSRNDArray(NDArray(jnp.zeros((0,), dt)),
                          NDArray(jnp.zeros((0,), jnp.int32)),
                          NDArray(jnp.zeros((shape[0] + 1,), jnp.int32)),
                          tuple(shape), ctx)
    return _dense_zeros(shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, BaseSparseNDArray):
        return source_array
    return _dense_array(source_array, ctx=ctx, dtype=dtype)


def cast_storage(arr, stype):
    """`cast_storage` op (`src/operator/tensor/cast_storage-inl.h`),
    vectorized — no python per-element loops."""
    if isinstance(arr, BaseSparseNDArray) and arr.stype == stype:
        return arr
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    dense = arr._data
    if stype == "row_sparse":
        nz = jnp.any((dense != 0).reshape(dense.shape[0], -1), axis=1)
        idx = jnp.nonzero(nz)[0]
        return RowSparseNDArray(NDArray(jnp.take(dense, idx, axis=0)),
                                NDArray(idx.astype(jnp.int32)),
                                dense.shape, arr._ctx)
    if stype == "csr":
        d = _np.asarray(dense)
        rows, cols = _np.nonzero(d)
        order = _np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        indptr = _np.zeros(d.shape[0] + 1, _np.int64)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr)
        return CSRNDArray(NDArray(jnp.asarray(d[rows, cols])),
                          NDArray(jnp.asarray(cols.astype(_np.int64))),
                          NDArray(jnp.asarray(indptr)), d.shape, arr._ctx)
    raise MXNetError(f"unknown stype {stype}")


def retain(arr, indices):
    """sparse_retain (`src/operator/tensor/sparse_retain.cc`): keep only the
    requested rows — pure index math, never densifies."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    idx = indices._data if isinstance(indices, NDArray) else jnp.asarray(indices)
    idx = idx.astype(jnp.int32)
    keep = jnp.isin(arr.indices._data, idx)
    kept = jnp.nonzero(keep)[0]
    return RowSparseNDArray(
        NDArray(jnp.take(arr.data._data, kept, axis=0)),
        NDArray(jnp.take(arr.indices._data, kept)),
        arr.shape, arr._ctx)


def add(lhs, rhs):
    """row_sparse + row_sparse → row_sparse (gradient accumulation),
    via index union — never densifies."""
    assert isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray)
    assert lhs.shape == rhs.shape
    li, ri = lhs.indices._data, rhs.indices._data
    union = jnp.union1d(li, ri)
    pos_l = jnp.searchsorted(union, li)
    pos_r = jnp.searchsorted(union, ri)
    out = jnp.zeros((union.shape[0],) + lhs.shape[1:], lhs.data._data.dtype)
    out = out.at[pos_l].add(lhs.data._data)
    out = out.at[pos_r].add(rhs.data._data)
    return RowSparseNDArray(NDArray(out), NDArray(union.astype(jnp.int32)),
                            lhs.shape, lhs._ctx)


def square_sum(arr, axis=None, keepdims=False):
    """_square_sum over row_sparse (`square_sum.cc`) — operates on the
    stored rows only."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("square_sum expects a RowSparseNDArray")
    sq = arr.data._data * arr.data._data
    if axis is None:
        return NDArray(jnp.sum(sq).reshape((1,) * arr.ndim if keepdims else ()))
    if axis in (1, -1) and arr.ndim == 2:
        # per-row sums scattered back to full length
        out = jnp.zeros((arr.shape[0],), sq.dtype)
        out = out.at[arr.indices._data.astype(jnp.int32)].set(sq.sum(axis=1))
        if keepdims:
            out = out[:, None]
        return NDArray(out)
    raise MXNetError(f"square_sum: unsupported axis {axis}")


@jax.jit
def _csr_dot_dense(data, row_ids, cols, rhs, n_rows):
    contrib = data[:, None] * rhs[cols]
    return jax.ops.segment_sum(contrib, row_ids, num_segments=n_rows)


@jax.jit
def _csr_t_dot_dense(data, row_ids, cols, rhs, n_cols):
    contrib = data[:, None] * rhs[row_ids]
    return jax.ops.segment_sum(contrib, cols, num_segments=n_cols)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (`src/operator/tensor/dot.cc`):

    * csr × dense  → dense       (one segment_sum over nnz)
    * csrᵀ × dense → row_sparse-shaped dense cols (kept dense: result cols
      are generally dense) — the reference's dot(csr.T, dense) = row_sparse
      is honored by returning row_sparse when requested via forward_stype.
    """
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray):
        data = lhs.data._data
        cols = lhs.indices._data.astype(jnp.int32)
        row_ids = lhs._row_ids().astype(jnp.int32)
        if transpose_a:
            out = _csr_t_dot_dense(data, row_ids, cols, rhs._data,
                                   lhs.shape[1])
        else:
            out = _csr_dot_dense(data, row_ids, cols, rhs._data, lhs.shape[0])
        return NDArray(out, lhs._ctx)
    from . import invoke_nd

    return invoke_nd("dot", NDArray(lhs._data, lhs._ctx),
                     NDArray(rhs._data, rhs._ctx),
                     transpose_a=transpose_a, transpose_b=transpose_b)
