"""NDArray save/load.

Parity: `python/mxnet/ndarray/utils.py:149,222` (`mx.nd.save/load`) over the
reference's binary format (`src/ndarray/ndarray.cc:1578 Save / :1695 Load`).

Format: a single-file container with the reference's outer framing
(magic + reserved + names) so tooling can recognize it, carrying per-array
payloads as (dtype-flag, ndim, shape, raw bytes) — dense storage only for
now; sparse arrays save their compound parts.
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import _DTYPE_NP_TO_MX, _DTYPE_MX_TO_NP, np_dtype, MXNetError

_MAGIC = 0x112

__all__ = ["save", "load"]


def _write_array(f, arr):
    npv = arr.asnumpy() if hasattr(arr, "asnumpy") else _np.asarray(arr)
    flag = _DTYPE_NP_TO_MX.get(npv.dtype.type)
    if flag is None:
        npv = npv.astype(_np.float32)
        flag = 0
    f.write(struct.pack("<i", flag))
    f.write(struct.pack("<I", npv.ndim))
    for s in npv.shape:
        f.write(struct.pack("<q", s))
    f.write(npv.tobytes())


def _read_array(f):
    from .ndarray import array as _nd_array

    (flag,) = struct.unpack("<i", f.read(4))
    (ndim,) = struct.unpack("<I", f.read(4))
    shape = tuple(struct.unpack("<q", f.read(8))[0] for _ in range(ndim))
    dt = _np.dtype(_DTYPE_MX_TO_NP[flag])
    n = int(_np.prod(shape)) if shape else 1
    buf = f.read(n * dt.itemsize)
    npv = _np.frombuffer(buf, dtype=dt).reshape(shape)
    return _nd_array(npv, dtype=dt)


def save(fname, data):
    """Save NDArray / list / dict of NDArrays (parity `mx.nd.save`).

    The device fetch (`asnumpy`) happens on the calling thread; the
    serialization + disk write is PUSHED onto the native engine with a
    write-var keyed on the path (reference: checkpoint writes ride
    Engine::PushAsync with the output NDArray vars,
    `src/engine/threaded_engine.cc`), so training does not stall on disk.
    `load` and `engine.wait_all()` are the sync points; writes to the same
    path stay ordered by the path var."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        names, arrays = [], [data]
    elif isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        raise MXNetError("save expects NDArray, list or dict of NDArrays")
    # snapshot on the caller thread: the values written are the values at
    # save() time even if the caller mutates the arrays right after
    snaps = [a.asnumpy() if hasattr(a, "asnumpy") else _np.asarray(a)
             for a in arrays]

    from .. import engine

    if engine.async_io_enabled():
        # the file EXISTS when save() returns (callers legitimately check
        # that, and a tmpdir may be torn down before the engine runs) —
        # created WITHOUT truncating: overwriting an existing checkpoint
        # must keep the old content readable until the atomic replace in
        # _write_file lands (a crash before then loses only the new
        # write, never both). nd.load / wait_all are the content sync
        # points.
        open(fname, "ab").close()
        engine.push_io(fname, _write_file, fname, names, snaps)
    else:
        _write_file(fname, names, snaps)


def _write_file(fname, names, arrays):
    """Write to a temp file then atomically rename: an out-of-band reader
    racing the async engine sees the empty placeholder or the complete
    file, never torn content."""
    import os

    tmp = fname + ".tmp~"
    _write_payload(tmp, names, arrays)
    os.replace(tmp, fname)


def _write_payload(fname, names, arrays):
    with open(fname, "wb") as f:
        f.write(struct.pack("<Q", _MAGIC))
        f.write(struct.pack("<Q", 0))  # reserved
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_array(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname):
    """Load arrays saved by :func:`save` (parity `mx.nd.load`): waits for
    any pending async writes first (the read side of the engine's
    write-var ordering)."""
    from .. import engine

    if engine.async_io_enabled():
        engine.wait_all()
    with open(fname, "rb") as f:
        (magic,) = struct.unpack("<Q", f.read(8))
        if magic != _MAGIC:
            raise MXNetError(f"Invalid NDArray file format: {fname}")
        f.read(8)
        (n,) = struct.unpack("<Q", f.read(8))
        arrays = [_read_array(f) for _ in range(n)]
        (nn,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(nn):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode())
    if not names:
        return arrays
    return dict(zip(names, arrays))
