"""NDArray save/load.

Parity: `python/mxnet/ndarray/utils.py:149,222` (`mx.nd.save/load`) over the
reference's binary format (`src/ndarray/ndarray.cc:1578 Save / :1695 Load`).

Format: a single-file container with the reference's outer framing
(magic + reserved + names) so tooling can recognize it, carrying per-array
payloads as (dtype-flag, ndim, shape, raw bytes) — dense storage only for
now; sparse arrays save their compound parts.
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import _DTYPE_NP_TO_MX, _DTYPE_MX_TO_NP, np_dtype, MXNetError

_MAGIC = 0x112

__all__ = ["save", "load"]


def _write_array(f, arr):
    npv = arr.asnumpy() if hasattr(arr, "asnumpy") else _np.asarray(arr)
    flag = _DTYPE_NP_TO_MX.get(npv.dtype.type)
    if flag is None:
        npv = npv.astype(_np.float32)
        flag = 0
    f.write(struct.pack("<i", flag))
    f.write(struct.pack("<I", npv.ndim))
    for s in npv.shape:
        f.write(struct.pack("<q", s))
    f.write(npv.tobytes())


def _read_array(f):
    from .ndarray import array as _nd_array

    (flag,) = struct.unpack("<i", f.read(4))
    (ndim,) = struct.unpack("<I", f.read(4))
    shape = tuple(struct.unpack("<q", f.read(8))[0] for _ in range(ndim))
    dt = _np.dtype(_DTYPE_MX_TO_NP[flag])
    n = int(_np.prod(shape)) if shape else 1
    buf = f.read(n * dt.itemsize)
    npv = _np.frombuffer(buf, dtype=dt).reshape(shape)
    return _nd_array(npv, dtype=dt)


def save(fname, data):
    """Save NDArray / list / dict of NDArrays (parity `mx.nd.save`)."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        names, arrays = [], [data]
    elif isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        raise MXNetError("save expects NDArray, list or dict of NDArrays")
    with open(fname, "wb") as f:
        f.write(struct.pack("<Q", _MAGIC))
        f.write(struct.pack("<Q", 0))  # reserved
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_array(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname):
    """Load arrays saved by :func:`save` (parity `mx.nd.load`)."""
    with open(fname, "rb") as f:
        (magic,) = struct.unpack("<Q", f.read(8))
        if magic != _MAGIC:
            raise MXNetError(f"Invalid NDArray file format: {fname}")
        f.read(8)
        (n,) = struct.unpack("<Q", f.read(8))
        arrays = [_read_array(f) for _ in range(n)]
        (nn,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(nn):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode())
    if not names:
        return arrays
    return dict(zip(names, arrays))
