"""NDArray save/load.

Parity: `python/mxnet/ndarray/utils.py:149,222` (`mx.nd.save/load`) over the
reference's binary format (`src/ndarray/ndarray.cc:1578 Save / :1695 Load`).

Format: a single-file container with the reference's outer framing
(magic + reserved + names) so tooling can recognize it, carrying per-array
payloads as (dtype-flag, ndim, shape, raw bytes) — dense storage only for
now; sparse arrays save their compound parts.

Integrity (resilience layer): the reserved word carries a format version.
Version 1 appends a (crc32, length) footer after every array payload;
`load` verifies each footer and raises
:class:`~mxnet_tpu.resilience.CorruptCheckpointError` on a mismatch or a
short read, so `model.load_checkpoint` can fall back to the last good
epoch instead of silently training from garbage. Version-0 files (the
reference layout, no footers) still load, unverified.
"""
from __future__ import annotations

import struct
import time as _time
import zlib

import numpy as _np

from .. import telemetry
from ..base import _DTYPE_NP_TO_MX, _DTYPE_MX_TO_NP, np_dtype, MXNetError
from ..resilience import (CorruptCheckpointError, durable_replace, inject,
                          retry_call)

_MAGIC = 0x112
_VERSION = 1  # reserved word: 0 = reference layout, 1 = + per-array CRC footers

__all__ = ["save", "load", "checkpoint_intact"]


def _write_array(f, arr):
    npv = arr.asnumpy() if hasattr(arr, "asnumpy") else _np.asarray(arr)
    flag = _DTYPE_NP_TO_MX.get(npv.dtype.type)
    if flag is None:
        npv = npv.astype(_np.float32)
        flag = 0
    f.write(struct.pack("<i", flag))
    f.write(struct.pack("<I", npv.ndim))
    for s in npv.shape:
        f.write(struct.pack("<q", s))
    raw = npv.tobytes()
    f.write(raw)
    f.write(struct.pack("<Iq", zlib.crc32(raw) & 0xFFFFFFFF, len(raw)))


def _read_exact(f, n, fname):
    buf = f.read(n)
    if len(buf) != n:
        raise CorruptCheckpointError(
            f"{fname}: truncated array file (wanted {n} bytes, got {len(buf)})")
    return buf


def _scan_array(f, fname, has_footer, verify, want_data):
    """One array record: parse header, consume payload + footer. Returns
    the numpy value when ``want_data``, else streams the payload in 1 MiB
    chunks (CRC only — no materialization). EVERY malformed-header path
    raises CorruptCheckpointError so fallback loaders can catch it."""
    (flag,) = struct.unpack("<i", _read_exact(f, 4, fname))
    (ndim,) = struct.unpack("<I", _read_exact(f, 4, fname))
    shape = tuple(struct.unpack("<q", _read_exact(f, 8, fname))[0]
                  for _ in range(ndim))
    if flag not in _DTYPE_MX_TO_NP:
        raise CorruptCheckpointError(f"{fname}: bad dtype flag {flag}")
    if any(s < 0 for s in shape):
        raise CorruptCheckpointError(f"{fname}: negative shape {shape}")
    dt = _np.dtype(_DTYPE_MX_TO_NP[flag])
    total = (int(_np.prod(shape)) if shape else 1) * dt.itemsize
    if want_data:
        buf = _read_exact(f, total, fname)
        crc = zlib.crc32(buf) if verify else 0
    else:
        buf, crc, remaining = None, 0, total
        while remaining:
            chunk = f.read(min(remaining, 1 << 20))
            if not chunk:
                raise CorruptCheckpointError(
                    f"{fname}: truncated array payload")
            crc = zlib.crc32(chunk, crc)
            remaining -= len(chunk)
    if has_footer:  # footer bytes are part of the v1 layout even unverified
        want, length = struct.unpack("<Iq", _read_exact(f, 12, fname))
        if verify and (length != total or (crc & 0xFFFFFFFF) != want):
            raise CorruptCheckpointError(
                f"{fname}: CRC mismatch on array payload — checkpoint is corrupt")
    if not want_data:
        return None
    try:
        return _np.frombuffer(buf, dtype=dt).reshape(shape)
    except ValueError as e:
        raise CorruptCheckpointError(f"{fname}: bad array header: {e}") from e


def _parse_container(fname, want_data, verify):
    """The ONE parser of the on-disk container — `load` materializes from
    it, `checkpoint_intact` merely CRC-walks it — so the two can never
    diverge on what counts as a valid file."""
    with open(fname, "rb") as f:
        (magic,) = struct.unpack("<Q", _read_exact(f, 8, fname))
        if magic != _MAGIC:
            raise MXNetError(f"Invalid NDArray file format: {fname}")
        (version,) = struct.unpack("<Q", _read_exact(f, 8, fname))
        has_footer = version >= 1
        verify = has_footer and verify
        (n,) = struct.unpack("<Q", _read_exact(f, 8, fname))
        arrays = [_scan_array(f, fname, has_footer, verify, want_data)
                  for _ in range(n)]
        (nn,) = struct.unpack("<Q", _read_exact(f, 8, fname))
        names = []
        for _ in range(nn):
            (ln,) = struct.unpack("<Q", _read_exact(f, 8, fname))
            raw = _read_exact(f, ln, fname)
            try:
                names.append(raw.decode())
            except UnicodeDecodeError as e:
                raise CorruptCheckpointError(
                    f"{fname}: undecodable array name") from e
    return arrays, names


def save(fname, data):
    """Save NDArray / list / dict of NDArrays (parity `mx.nd.save`).

    The device fetch (`asnumpy`) happens on the calling thread; the
    serialization + disk write is PUSHED onto the native engine with a
    write-var keyed on the path (reference: checkpoint writes ride
    Engine::PushAsync with the output NDArray vars,
    `src/engine/threaded_engine.cc`), so training does not stall on disk.
    `load` and `engine.wait_all()` are the sync points; writes to the same
    path stay ordered by the path var. Transient write failures are
    absorbed by the resilience retry budget on either path."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        names, arrays = [], [data]
    elif isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        raise MXNetError("save expects NDArray, list or dict of NDArrays")
    # snapshot on the caller thread: the values written are the values at
    # save() time even if the caller mutates the arrays right after
    snaps = [a.asnumpy() if hasattr(a, "asnumpy") else _np.asarray(a)
             for a in arrays]
    if telemetry._enabled:
        telemetry.counter("checkpoint.saves").inc()
        telemetry.counter("checkpoint.save_bytes").inc(
            sum(s.nbytes for s in snaps))

    from .. import engine

    if engine.async_io_enabled():
        # the file EXISTS when save() returns (callers legitimately check
        # that, and a tmpdir may be torn down before the engine runs) —
        # created WITHOUT truncating: overwriting an existing checkpoint
        # must keep the old content readable until the atomic replace in
        # _write_file lands (a crash before then loses only the new
        # write, never both). nd.load / wait_all are the content sync
        # points.
        open(fname, "ab").close()
        engine.push_io(fname, _write_file, fname, names, snaps)
    else:
        retry_call(_write_file, fname, names, snaps, desc=fname)


def _write_file(fname, names, arrays):
    """Write to a temp file, fsync, then atomically rename: an out-of-band
    reader racing the async engine sees the empty placeholder or the
    complete file, never torn content — and the fsync-before-rename means
    a host crash right after the rename cannot leave a renamed file whose
    data pages never hit disk (the torn-after-crash case CRC verification
    exists to catch, closed at the source). The `write` fault point covers
    both the transient-EIO and torn-write (truncate=K) injection cases."""
    import os

    tele = telemetry._enabled  # cached: enable() racing this write must
    t0 = _time.perf_counter() if tele else 0.0  # not record a bogus sample
    rule = inject("write", fname)
    tmp = fname + ".tmp~"
    _write_payload(tmp, names, arrays)
    if rule is not None and rule.truncate is not None:
        with open(tmp, "rb+") as f:
            f.truncate(rule.truncate)
            f.flush()
            os.fsync(f.fileno())
    durable_replace(tmp, fname)  # rename made durable (dir fsync)
    if tele:
        # true wall time of serialize+fsync+rename — runs on the engine
        # worker in async mode, so this (not save()'s dispatch time) is the
        # real disk cost of a checkpoint
        telemetry.histogram("checkpoint.write_us").record(
            (_time.perf_counter() - t0) * 1e6)


def _write_payload(fname, names, arrays):
    import os

    with open(fname, "wb") as f:
        f.write(struct.pack("<Q", _MAGIC))
        f.write(struct.pack("<Q", _VERSION))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_array(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)
        f.flush()
        os.fsync(f.fileno())


def checkpoint_intact(fname):
    """True iff ``fname`` parses end-to-end as a saved array file, with
    every v1 CRC footer verified (always — `MXNET_CHECKPOINT_VERIFY` only
    relaxes `load`): a streaming scan cheap enough for checkpoint
    retention to run before evicting the fallback epochs. Does NOT wait
    on the engine; callers sequence themselves against in-flight writes."""
    try:
        _parse_container(fname, want_data=False, verify=True)
    except (MXNetError, OSError, struct.error):
        return False
    return True


def load(fname):
    """Load arrays saved by :func:`save` (parity `mx.nd.load`): waits for
    any pending async writes first (the read side of the engine's
    write-var ordering), then verifies per-array CRC footers (version-1
    files; `MXNET_CHECKPOINT_VERIFY=0` skips the check)."""
    from ..base import getenv
    from .ndarray import array as _nd_array
    from .. import engine

    if engine.async_io_enabled():
        engine.wait_all()
    tele = telemetry._enabled
    t0 = _time.perf_counter() if tele else 0.0
    try:
        raw, names = _parse_container(
            fname, want_data=True,
            verify=bool(getenv("MXNET_CHECKPOINT_VERIFY")))
    except CorruptCheckpointError:
        if tele:
            telemetry.counter("checkpoint.corrupt").inc()
        raise
    if tele:
        telemetry.counter("checkpoint.loads").inc()
        telemetry.counter("checkpoint.load_bytes").inc(
            sum(npv.nbytes for npv in raw))
        telemetry.histogram("checkpoint.load_us").record(
            (_time.perf_counter() - t0) * 1e6)
    arrays = [_nd_array(npv, dtype=npv.dtype) for npv in raw]
    if not names:
        return arrays
    return dict(zip(names, arrays))
