"""Control-flow operators — foreach / while_loop / cond.

Parity: reference `src/operator/control_flow.cc` (`_foreach`:1255,
`_while_loop`:1316, `_cond`:1378) and the python frontends
`python/mxnet/ndarray/contrib.py` (foreach/while_loop/cond taking python
callables over NDArrays).

TPU-native design: the body callables are traced ONCE into
``lax.scan`` / masked-scan / ``lax.cond`` programs — compiler-friendly
control flow with static shapes, instead of the reference's per-step
subgraph executor loop.  ``while_loop`` is lowered to a bounded
``lax.scan`` over ``max_iterations`` with an `active` mask, which makes it
reverse-mode differentiable (``lax.while_loop`` is not) and keeps the trip
count static for XLA.

Free variables: closure-captured NDArrays inside the body (e.g. the weights
of a layer called per step) are discovered in an abstract ``eval_shape``
pass and promoted to explicit inputs of the traced function, so gradients
flow to them — see ``register._resolve_nd_data``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ndarray import NDArray
from . import register as _register
from ..util import flatten_nested, unflatten_nested as _unflatten

__all__ = ["foreach", "while_loop", "cond"]


def _flatten(x):
    """x: NDArray | list/tuple (possibly nested) -> (flat list, structure)."""
    return flatten_nested(x, NDArray)


def _capture_run(pure_core, explicit_nds, warmup=None):
    """Trace `pure_core(list_of_jax_arrays) -> tuple` with free-variable
    capture; returns flat list[NDArray] outputs, recording one tape node
    when autograd is on."""
    from .. import autograd

    # eager warm-up: run the body once OUTSIDE any trace so shape-dependent
    # side effects (gluon deferred parameter init on first call) happen with
    # concrete values instead of leaking tracers into parameter storage
    if warmup is not None:
        with autograd.pause():
            warmup()

    frames = _register._cf_frames()

    # discovery pass: abstract trace collecting concrete NDArrays the body
    # touches through op dispatch
    frame = {"subst": {}, "collect": {}}
    frames.append(frame)
    try:
        jax.eval_shape(lambda *a: pure_core(list(a)),
                       *[n._data for n in explicit_nds])
    finally:
        frames.pop()
    captured = [n for n in frame["collect"].values()]

    n_exp = len(explicit_nds)

    def pure(*arrays):
        exp, cap = arrays[:n_exp], arrays[n_exp:]
        fr = {"subst": {id(n): t for n, t in zip(captured, cap)},
              "collect": None}
        frames.append(fr)
        try:
            out = pure_core(list(exp))
        finally:
            frames.pop()
        return out if len(out) != 1 else out[0]

    all_nds = list(explicit_nds) + captured
    arrays = [n._data for n in all_nds]
    if autograd.is_recording():
        outs, vjp = jax.vjp(pure, *arrays)
    else:
        outs = pure(*arrays)
        vjp = None
    outs_t = (outs,) if not isinstance(outs, tuple) else outs
    ctx = explicit_nds[0]._ctx if explicit_nds else None
    out_nds = [NDArray(o, ctx) for o in outs_t]
    if vjp is not None:
        autograd._record_node(
            vjp, all_nds, out_nds,
            [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs_t])
    return out_nds


def foreach(body, data, init_states, name="foreach"):
    """Run `body(data_slice, states) -> (outputs, new_states)` over the
    leading axis of `data`; outputs are stacked along a new leading axis.
    Lowered to one `lax.scan` (reference `_foreach`, control_flow.cc:1255).
    """
    from .. import autograd

    data_l, data_struct = _flatten(data)
    states_l, states_struct = _flatten(init_states)
    if not data_l:
        raise ValueError("foreach: data must contain at least one NDArray")
    n_data = len(data_l)
    meta = {}

    def pure_core(exp):
        d, s = exp[:n_data], exp[n_data:]

        def step(carry, xs):
            with autograd.pause():
                x_nd = _unflatten([NDArray(x) for x in xs], data_struct)
                s_nd = _unflatten([NDArray(c) for c in carry], states_struct)
                out, new_s = body(x_nd, s_nd)
                out_l, out_struct = _flatten(out)
                ns_l, ns_struct = _flatten(new_s)
                if len(ns_l) != len(carry):
                    raise ValueError(
                        f"foreach: body returned {len(ns_l)} states, "
                        f"expected {len(carry)}")
                meta["out_struct"], meta["n_out"] = out_struct, len(out_l)
                meta["ns_struct"] = ns_struct
            return tuple(n._data for n in ns_l), tuple(o._data for o in out_l)

        carry, ys = lax.scan(step, tuple(s), tuple(d))
        return tuple(ys) + tuple(carry)

    def warmup():
        body(_unflatten([NDArray(d._data[0]) for d in data_l], data_struct),
             _unflatten([NDArray(s._data) for s in states_l], states_struct))

    out_nds = _capture_run(pure_core, data_l + states_l, warmup)
    n_out = meta["n_out"]
    outputs = _unflatten(out_nds[:n_out], meta["out_struct"]) if n_out else []
    states = _unflatten(out_nds[n_out:], meta["ns_struct"]) if out_nds[n_out:] else []
    return outputs, states


def while_loop(cond, func, loop_vars, max_iterations=None, name="while_loop"):
    """`while cond(*loop_vars): outputs, loop_vars = func(*loop_vars)`.

    Reference `_while_loop` (control_flow.cc:1316).  Lowered to a bounded
    `lax.scan` over `max_iterations` with an activity mask: static trip
    count (XLA-friendly) and reverse-mode differentiable.  Step outputs are
    stacked to shape (max_iterations, ...); rows past the actual step count
    are zero (the reference's symbolic path pads identically).  Returns
    (outputs, final_loop_vars).
    """
    from .. import autograd

    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations (static trip "
                         "count for XLA)")
    max_iterations = int(max_iterations)
    lv_l, lv_struct = _flatten(loop_vars)
    if not lv_l:
        raise ValueError("while_loop: loop_vars must be non-empty")
    meta = {}

    def pure_core(exp):
        def step(carry, _):
            lv, active = carry
            with autograd.pause():
                lv_nd = _unflatten([NDArray(a) for a in lv], lv_struct)
                lv_list = lv_nd if isinstance(lv_nd, list) else [lv_nd]
                c = cond(*lv_list)
                cval = jnp.reshape(c._data, ()).astype(bool)
                act = jnp.logical_and(active, cval)
                out, new_lv = func(*lv_list)
                out_l, out_struct = _flatten(out)
                nl_l, _ = _flatten(new_lv)
                if len(nl_l) != len(lv):
                    raise ValueError(
                        f"while_loop: func returned {len(nl_l)} loop_vars, "
                        f"expected {len(lv)}")
                meta["out_struct"], meta["n_out"] = out_struct, len(out_l)
            new_carry = tuple(jnp.where(act, n._data, o)
                              for n, o in zip(nl_l, lv))
            ys = tuple(jnp.where(act, o._data, jnp.zeros_like(o._data))
                       for o in out_l)
            return (new_carry, act), ys

        (carry, _), ys = lax.scan(
            step, (tuple(exp), jnp.bool_(True)), None, length=max_iterations)
        return tuple(ys) + tuple(carry)

    def warmup():
        lv_nd = _unflatten([NDArray(a._data) for a in lv_l], lv_struct)
        lv_list = lv_nd if isinstance(lv_nd, list) else [lv_nd]
        cond(*lv_list)
        func(*lv_list)

    out_nds = _capture_run(pure_core, lv_l, warmup)
    n_out = meta["n_out"]
    outputs = _unflatten(out_nds[:n_out], meta["out_struct"]) if n_out else []
    final_lv = _unflatten(out_nds[n_out:], lv_struct)
    return outputs, final_lv


def cond(pred, then_func, else_func, name="cond"):
    """`then_func() if pred else else_func()` as one traced `lax.cond`
    (reference `_cond`, control_flow.cc:1378).  Both branches must return
    the same structure/shapes."""
    from .. import autograd

    if not isinstance(pred, NDArray):
        raise TypeError("cond: pred must be an NDArray scalar")
    meta = {}

    def pure_core(exp):
        pv = jnp.reshape(exp[0], ()).astype(bool)

        def mk(branch, tag):
            def f(_):
                with autograd.pause():
                    out = branch()
                    out_l, out_struct = _flatten(out)
                    meta["out_struct"], meta["n_out"] = out_struct, len(out_l)
                    return tuple(o._data for o in out_l)
            return f

        res = lax.cond(pv, mk(then_func, "then"), mk(else_func, "else"), None)
        return tuple(res)

    def warmup():
        then_func()
        else_func()

    out_nds = _capture_run(pure_core, [pred], warmup)
    return _unflatten(out_nds, meta["out_struct"])
