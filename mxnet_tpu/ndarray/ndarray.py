"""NDArray — the framework's value type.

Parity: `include/mxnet/ndarray.h:82` + `python/mxnet/ndarray/ndarray.py`.

TPU-native redesign: an NDArray wraps a `jax.Array`. The reference's
engine-variable machinery (read/write vars, `WaitToRead/WaitToWrite`) is
subsumed by XLA's async dispatch — every jax op is enqueued asynchronously
and `wait_to_read` maps to `block_until_ready`. Mutation (`x[:] = v`,
``out=`` kwargs, optimizer updates) is rendered functionally: the wrapper
swaps its underlying buffer, which is exactly the version-bump the
reference's `ThreadedVar` performed (`threaded_engine.h:119`).

Divergence (documented): slicing returns a copy-on-write functional view,
not an aliased buffer; writes through a slice do not propagate to the
parent (XLA buffers are immutable). `__setitem__` on the parent works.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import MXNetError, np_dtype, integer_types, numeric_types
from ..context import Context, current_context, cpu
from ..lazy.graph import LazyArray as _LazyArray
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange", "concatenate", "waitall"]


def _dtype_name(dt):
    dt = _np.dtype(dt)
    name = dt.name
    return name


class NDArray:
    __slots__ = (
        "_buf", "_ctx", "grad", "grad_req", "_ag_marked", "_stype",
        "_fresh_grad", "__weakref__",
    )

    def __init__(self, data, ctx=None, stype="default"):
        self._buf = data
        self._ctx = ctx if ctx is not None else _ctx_of(data)
        self.grad = None
        self.grad_req = "null"
        self._ag_marked = False
        self._stype = stype
        # True once backward() has written this array's grad; cleared by
        # Trainer._update (reference NDArray::fresh_out_grad, trainer.py:401)
        self._fresh_grad = False

    # -- basic properties ---------------------------------------------------

    @property
    def _data(self):
        """The concrete jax array — THE materialization barrier. Under
        ``MXNET_LAZY=1`` the buffer may be a pending
        :class:`~mxnet_tpu.lazy.graph.LazyArray`; reading ``_data``
        flushes the owning segment (one fused XLA program) and swaps the
        realized buffer in. Every concrete-value escape in the codebase —
        ``asnumpy``, kvstore pushes, checkpoint writes, executor feeds —
        reads through here, which is what makes the barrier audit
        structural rather than a site-by-site hunt. Metadata queries
        (``shape``/``dtype``/``ndim``/``size``) read ``_buf`` and never
        flush."""
        buf = self._buf
        if type(buf) is _LazyArray:
            buf = buf.force()
            self._buf = buf
        return buf

    @_data.setter
    def _data(self, value):
        # a buffer swap IS the version bump: nodes that recorded the old
        # value keep referencing it (reference ThreadedVar versioning)
        self._buf = value

    @property
    def shape(self):
        return tuple(self._buf.shape)

    @property
    def dtype(self):
        return _np.dtype(self._buf.dtype)

    @property
    def ndim(self):
        return len(self._buf.shape)

    @property
    def size(self):
        n = 1
        for s in self._buf.shape:
            n *= int(s)
        return n

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return self._stype

    @property
    def handle(self):
        return self._data  # "handle" is the jax array itself

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        return f"\n{_np.asarray(self._data)}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def __str__(self):
        return self.__repr__()

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous.")

    # -- conversion ---------------------------------------------------------

    def asnumpy(self):
        """Blocking copy to host (reference `WaitToRead` + copy)."""
        buf = self._buf
        if type(buf) is _LazyArray:
            self._buf = buf = buf.force("asnumpy")
        return _np.asarray(buf)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def astype(self, dtype, copy=True):
        return _invoke("Cast", self, dtype=_dtype_name(np_dtype(dtype)))

    def as_in_context(self, ctx):
        if ctx == self._ctx:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device), ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other._ctx.jax_device)
            return other
        if isinstance(other, Context):
            return self.as_in_context(other)
        raise TypeError(f"copyto does not support type {type(other)}")

    def copy(self):
        return NDArray(jnp.array(self._data), self._ctx)

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp

        return _sp.cast_storage(self, stype)

    # -- engine-var parity --------------------------------------------------

    def wait_to_read(self):
        buf = self._buf
        if type(buf) is _LazyArray:
            self._buf = buf = buf.force("wait")
        buf.block_until_ready()

    def wait_to_write(self):
        self.wait_to_read()

    # -- autograd -----------------------------------------------------------

    def attach_grad(self, grad_req="write", stype=None):
        """Allocate gradient buffer (parity `ndarray.py attach_grad`).
        ``stype='row_sparse'`` allocates a row-sparse buffer: backward then
        deposits only the touched rows (never the dense table)."""
        if stype == "row_sparse":
            from .sparse import RowSparseNDArray

            self.grad = RowSparseNDArray(
                NDArray(jnp.zeros((0,) + tuple(self.shape[1:]), self.dtype)),
                NDArray(jnp.zeros((0,), jnp.int32)),
                tuple(self.shape), self._ctx)
        else:
            self.grad = NDArray(jnp.zeros(self.shape, self.dtype), self._ctx)
        self.grad_req = grad_req
        self._ag_marked = True

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        # shares the (possibly still-pending) buffer — detaching must not
        # force a segment flush
        out = NDArray(self._buf, self._ctx)
        return out

    # -- shape ops (methods) ------------------------------------------------

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if not shape:
            shape = kwargs.get("shape")
        return _invoke("Reshape", self, shape=shape, reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return _invoke("Reshape", self, shape=other.shape)

    def expand_dims(self, axis):
        return _invoke("expand_dims", self, axis=axis)

    def squeeze(self, axis=None):
        return _invoke("squeeze", self, axis=axis)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return _invoke("transpose", self, axes=axes if axes else None)

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return _invoke("Flatten", self)

    def flip(self, axis):
        return _invoke("reverse", self, axis=axis)

    def tile(self, reps):
        return _invoke("tile", self, reps=reps)

    def repeat(self, repeats, axis=None):
        return _invoke("repeat", self, repeats=repeats, axis=axis)

    def swapaxes(self, dim1, dim2):
        return _invoke("SwapAxis", self, dim1=dim1, dim2=dim2)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _invoke("SliceChannel", self, num_outputs=num_outputs, axis=axis,
                       squeeze_axis=squeeze_axis)

    def slice(self, begin, end, step=None):
        return _invoke("slice", self, begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end):
        return _invoke("slice_axis", self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return _invoke("take", self, indices, axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False):
        return _invoke("pick", self, index, axis=axis, keepdims=keepdims)

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return _invoke("one_hot", self, depth=depth, on_value=on_value, off_value=off_value,
                       dtype=dtype)

    def broadcast_to(self, shape):
        return _invoke("broadcast_to", self, shape=shape)

    def broadcast_like(self, other):
        return _invoke("broadcast_like", self, other)

    def diag(self, k=0):
        return _invoke("diag", self, k=k)

    # -- reductions ---------------------------------------------------------

    def sum(self, axis=None, keepdims=False, **kw):
        return _invoke("sum", self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return _invoke("mean", self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False, **kw):
        return _invoke("prod", self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return _invoke("max", self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return _invoke("min", self, axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return _invoke("norm", self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return _invoke("argmax", self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return _invoke("argmin", self, axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return _invoke("argsort", self, axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return _invoke("sort", self, axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return _invoke("topk", self, axis=axis, k=k, ret_typ=ret_typ, is_ascend=is_ascend)

    def clip(self, a_min, a_max):
        return _invoke("clip", self, a_min=a_min, a_max=a_max)

    def abs(self):
        return _invoke("abs", self)

    def sign(self):
        return _invoke("sign", self)

    def exp(self):
        return _invoke("exp", self)

    def log(self):
        return _invoke("log", self)

    def sqrt(self):
        return _invoke("sqrt", self)

    def square(self):
        return _invoke("square", self)

    def sigmoid(self):
        return _invoke("sigmoid", self)

    def tanh(self):
        return _invoke("tanh", self)

    def relu(self):
        return _invoke("relu", self)

    def softmax(self, axis=-1):
        return _invoke("softmax", self, axis=axis)

    def log_softmax(self, axis=-1):
        return _invoke("log_softmax", self, axis=axis)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _invoke("dot", self, other, transpose_a=transpose_a, transpose_b=transpose_b)

    def as_nd_ndarray(self):
        return self

    # -- arithmetic ---------------------------------------------------------

    def _binary(self, other, op, scalar_op, rop=None):
        if isinstance(other, NDArray):
            return _invoke(op, self, other)
        if isinstance(other, numeric_types):
            return _invoke(scalar_op, self, scalar=float(other))
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return _invoke("_rminus_scalar", self, scalar=float(o))

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return _invoke("_rdiv_scalar", self, scalar=float(o))

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return _invoke("_rmod_scalar", self, scalar=float(o))

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return _invoke("_rpower_scalar", self, scalar=float(o))

    def __neg__(self):
        return _invoke("negative", self)

    def __abs__(self):
        return _invoke("abs", self)

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __iadd__(self, o):
        out = self.__add__(o)
        self._data = out._data
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._data = out._data
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._data = out._data
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._data = out._data
        return self

    # -- indexing -----------------------------------------------------------

    def __getitem__(self, key):
        from .. import autograd

        if not autograd.is_recording():
            # lazy capture (MXNET_LAZY=1): basic int/slice reads record a
            # `slice` node into the pending segment instead of forcing a
            # flush — optimizer/eval code that slices mid-loop keeps its
            # whole segment fused (ROADMAP lazy item; segments-unchanged
            # + bit-parity pinned by test_lazy.py)
            lazied = self._lazy_basic_getitem(key)
            if lazied is not None:
                return lazied
        key = _convert_index(key)
        if autograd.is_recording():
            # recorded read: gradients must flow through slicing
            # (`ops/indexing._ag_getitem`; scatter-add back into the
            # source's cotangent via jax's gather vjp)
            from .register import invoke_nd

            return invoke_nd("_ag_getitem", self, key=(key,))
        out = self._data[key]
        return NDArray(out, self._ctx)

    def _basic_slice_key(self, key):
        """Normalize a basic int/slice key into (begin, end, step,
        int_axes) over explicit leading axes, or None for anything the
        slice/scatter ops cannot express statically (arrays, bools,
        Ellipsis, newaxis, negative steps)."""
        keys = key if isinstance(key, tuple) else (key,)
        if len(keys) > self.ndim or not all(
                isinstance(k, (slice, int, _np.integer))
                and not isinstance(k, (bool, _np.bool_)) for k in keys):
            # bools subclass int but mean mask/new-axis semantics, not a
            # position — they (and arrays/Ellipsis/None) stay eager
            return None
        begin, end, step, int_axes = [], [], [], []
        for d, k in enumerate(keys):
            if isinstance(k, (int, _np.integer)):
                k = int(k)
                if k < 0:
                    k += self.shape[d]
                if not 0 <= k < self.shape[d]:
                    return None  # out of range: the eager path raises
                begin.append(k); end.append(k + 1); step.append(1)
                int_axes.append(d)
            else:
                if k.step is not None and int(k.step) < 0:
                    return None  # negative-step windows stay eager
                # resolve to concrete ints (python slice semantics over
                # the STATIC shape) — the slice/scatter op attr parsers
                # take int tuples, not Nones
                b, e, s = k.indices(self.shape[d])
                begin.append(b); end.append(e); step.append(s)
        return tuple(begin), tuple(end), tuple(step), tuple(int_axes)

    def _lazy_basic_getitem(self, key):
        """The captured rendering of a basic read: `slice` (+ `reshape`
        to drop integer axes) recorded into the owning segment. Returns
        None when capture is off or the key is not basic — caller runs
        the eager path (which flushes a pending segment)."""
        from ..lazy import graph as _lazy

        if not _lazy.enabled():
            return None
        basic = self._basic_slice_key(key)
        if basic is None:
            return None
        begin, end, step, int_axes = basic
        from .register import invoke_nd

        if int_axes and len(int_axes) == self.ndim:
            return None  # scalar read — about to escape anyway; stay eager
        out = invoke_nd("slice", self, begin=begin, end=end, step=step)
        if int_axes:
            shape = tuple(s for d, s in enumerate(out.shape)
                          if d not in set(int_axes))
            out = invoke_nd("reshape", out, shape=shape)
        return out

    def __setitem__(self, key, value):
        from .. import autograd

        if autograd.is_recording() and self._recorded_setitem(key, value):
            return
        if not autograd.is_recording() and self._lazy_basic_setitem(key, value):
            return
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, (_np.ndarray, list, tuple, float, int)):
            value = jnp.asarray(value, dtype=self.dtype)
        if isinstance(key, slice) and key == slice(None):
            self._data = jnp.broadcast_to(value, self.shape).astype(self.dtype)
            return
        key = _convert_index(key)
        self._data = self._data.at[key].set(value.astype(self.dtype) if hasattr(value, "astype") else value)

    def _lazy_basic_setitem(self, key, value):
        """The captured rendering of a basic write: `_slice_assign(_scalar)`
        recorded into the pending segment, the result's buffer swapped in
        (the swap IS the version bump — nodes that recorded the old value
        keep referencing it). Returns False when capture is off / the key
        or value is not basic — caller runs the eager scatter (which
        flushes a pending segment)."""
        from ..lazy import graph as _lazy

        if not _lazy.enabled():
            return False
        basic = self._basic_slice_key(key)
        if basic is None:
            return False
        begin, end, step, _int_axes = basic
        from .register import invoke_nd

        if isinstance(value, numeric_types):
            out = invoke_nd("_slice_assign_scalar", self, begin=begin,
                            end=end, step=step, scalar=float(value))
        else:
            if not isinstance(value, NDArray):
                try:
                    value = NDArray(jnp.asarray(value, dtype=self.dtype),
                                    self._ctx)
                except (TypeError, ValueError):
                    return False
            out = invoke_nd("_slice_assign", self, value,
                            begin=begin, end=end, step=step)
        # share the PENDING buffer (out._buf) instead of reading
        # out._data — reading it would flush the very segment the write
        # just joined (the PR 10 out= precedent)
        self._buf = out._buf
        return True

    def _recorded_setitem(self, key, value):
        """Differentiable sliced write (`nd[a:b] = v` inside autograd.record).

        The reference forbids in-place writes to arrays in the graph
        (`imperative.cc` RecordOp's AGInfo check); here the write is
        FUNCTIONAL — `_slice_assign` (`matrix_op.cc:477`) — so gradients
        flow both around the window (to the pre-write value) and into the
        window (to `value`). The pre-write value becomes a fresh tape
        identity; if `self` was a marked leaf the mark (and grad buffer)
        moves to it, so `self.grad` after backward is the gradient wrt the
        value `self` held when recording reached this write.

        Returns True when the write was handled (basic int/slice keys);
        advanced (array) keys fall back to the raw in-place path."""
        keys = key if isinstance(key, tuple) else (key,)
        if not all(isinstance(k, (slice, int, _np.integer))
                   and not isinstance(k, (bool, _np.bool_)) for k in keys) \
                or len(keys) > self.ndim:
            # bools subclass int but mean mask/new-axis semantics, not a
            # position (the _basic_slice_key guard) — raw path handles them
            return False
        begin, end, step = [], [], []
        for k in keys:
            if isinstance(k, (int, _np.integer)):
                k = int(k)
                if k < 0:
                    k += self.shape[len(begin)]
                begin.append(k); end.append(k + 1); step.append(1)
            else:
                if k.step is not None and int(k.step) < 0:
                    return False  # negative-step writes stay on the raw path
                begin.append(k.start); end.append(k.stop); step.append(k.step or 1)
        old = NDArray(self._buf, self._ctx)
        old.grad, old.grad_req = self.grad, self.grad_req
        old._ag_marked, self._ag_marked = self._ag_marked, False
        from .. import autograd
        from .register import invoke_nd

        autograd._retarget(self, old)
        if isinstance(value, numeric_types):
            out = invoke_nd("_slice_assign_scalar", old, begin=tuple(begin),
                            end=tuple(end), step=tuple(step),
                            scalar=float(value))
        else:
            if not isinstance(value, NDArray):
                value = NDArray(jnp.asarray(value, dtype=self.dtype), self._ctx)
            out = invoke_nd("_slice_assign", old, value, begin=tuple(begin),
                            end=tuple(end), step=tuple(step))
        autograd._retarget(out, self)
        self._data = out._data
        return True

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # -- pickling ------------------------------------------------------------

    def __getstate__(self):
        import copyreg

        self._data  # materialize: a pending lazy buffer must not pickle
        names = copyreg._slotnames(type(self))
        return (None, {n: getattr(self, n) for n in names
                       if n != "__weakref__" and hasattr(self, n)})

    def __setstate__(self, state):
        _, slots = state
        for k, v in (slots or {}).items():
            setattr(self, k, v)

    # -- serialization ------------------------------------------------------

    def save(self, fname):
        from .utils import save

        save(fname, self)


def _convert_index(key):
    if isinstance(key, NDArray):
        return key._data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_convert_index(k) for k in key)
    if isinstance(key, _np.ndarray):
        return key
    return key


def _ctx_of(data):
    try:
        dev = list(data.devices())[0]
        if dev.platform == "cpu":
            return cpu(dev.id)
        from ..context import tpu

        return tpu(_accel_index(dev))
    except Exception:
        return cpu(0)


def _accel_index(dev):
    import jax as _jax

    accels = [d for d in _jax.local_devices() if d.platform != "cpu"]
    for i, d in enumerate(accels):
        if d == dev:
            return i
    return 0


def _invoke(op_name, *args, **kwargs):
    from .register import invoke_nd

    return invoke_nd(op_name, *args, **kwargs)


# ---------------------------------------------------------------------------
# creation helpers (parity: python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------


def _place(jarr, ctx):
    ctx = ctx if ctx is not None else current_context()
    if ctx.device_type in ("cpu", "cpu_pinned", "cpu_shared") and _default_is_cpu():
        return NDArray(jarr, ctx)
    return NDArray(jax.device_put(jarr, ctx.jax_device), ctx)


def _default_is_cpu():
    return jax.default_backend() == "cpu"


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        src = source_array._data
        dtype = dtype or source_array.dtype
    else:
        src = _np.asarray(source_array)
        if dtype is None:
            dtype = src.dtype if src.dtype != _np.float64 else _np.float32
    return _place(jnp.asarray(src, dtype=np_dtype(dtype)), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    return _place(jnp.zeros(_shape_t(shape), dtype=np_dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    return _place(jnp.ones(_shape_t(shape), dtype=np_dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    return _place(jnp.full(_shape_t(shape), val, dtype=np_dtype(dtype)), ctx)


def arange(start, stop=None, step=1.0, repeat=1, infer_range=False, ctx=None, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=np_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return _place(out, ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return _invoke("Concat", *arrays, dim=axis, num_args=len(arrays))


def _shape_t(shape):
    if isinstance(shape, integer_types):
        return (int(shape),)
    return tuple(int(s) for s in shape)


_PY_SCALAR_FN = {
    "broadcast_add": lambda a, b: a + b, "broadcast_sub": lambda a, b: a - b,
    "broadcast_mul": lambda a, b: a * b, "broadcast_div": lambda a, b: a / b,
    "broadcast_mod": lambda a, b: a % b, "broadcast_power": lambda a, b: a ** b,
    "broadcast_maximum": max, "broadcast_minimum": min,
    "broadcast_hypot": lambda a, b: (a * a + b * b) ** 0.5,
    "broadcast_equal": lambda a, b: float(a == b),
    "broadcast_not_equal": lambda a, b: float(a != b),
    "broadcast_greater": lambda a, b: float(a > b),
    "broadcast_greater_equal": lambda a, b: float(a >= b),
    "broadcast_lesser": lambda a, b: float(a < b),
    "broadcast_lesser_equal": lambda a, b: float(a <= b),
}


def _ufunc_helper(lhs, rhs, fn_array, fn_scalar, rfn_scalar=None):
    """Dispatch array/scalar combinations (parity `ndarray.py _ufunc_helper`)."""
    from .register import invoke_nd

    if isinstance(lhs, numeric_types):
        if isinstance(rhs, numeric_types):
            return _PY_SCALAR_FN[fn_array](lhs, rhs)
        return invoke_nd(rfn_scalar or fn_scalar, rhs, scalar=float(lhs))
    if isinstance(rhs, numeric_types):
        return invoke_nd(fn_scalar, lhs, scalar=float(rhs))
    return invoke_nd(fn_array, lhs, rhs)


def maximum(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_maximum", "_maximum_scalar")


def minimum(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_minimum", "_minimum_scalar")


def power(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_power", "_power_scalar", "_rpower_scalar")


def hypot(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_hypot", "_hypot_scalar")


def add(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_add", "_plus_scalar")


def subtract(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_sub", "_minus_scalar", "_rminus_scalar")


def multiply(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_mul", "_mul_scalar")


def divide(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_div", "_div_scalar", "_rdiv_scalar")


def modulo(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_mod", "_mod_scalar", "_rmod_scalar")


def equal(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_equal", "_equal_scalar")


def not_equal(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_not_equal", "_not_equal_scalar")


def greater(lhs, rhs):
    # scalar-lhs mirrors to the opposite comparison: 2 > x  ==  x < 2
    return _ufunc_helper(lhs, rhs, "broadcast_greater", "_greater_scalar", "_lesser_scalar")


def greater_equal(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_greater_equal", "_greater_equal_scalar",
                         "_lesser_equal_scalar")


def lesser(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_lesser", "_lesser_scalar", "_greater_scalar")


def lesser_equal(lhs, rhs):
    return _ufunc_helper(lhs, rhs, "broadcast_lesser_equal", "_lesser_equal_scalar",
                         "_greater_equal_scalar")


def true_divide(lhs, rhs):
    return divide(lhs, rhs)


def waitall():
    """Block until all async work completes (parity `mx.nd.waitall`) —
    including every thread's pending lazy segment."""
    from ..lazy.graph import flush_all

    flush_all("wait")
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    try:
        jax.block_until_ready(jnp.zeros(()))
    except Exception:
        pass


def moveaxis(tensor, source, destination):
    return NDArray(jnp.moveaxis(tensor._data, source, destination), tensor._ctx)


def onehot_encode(indices, out):
    res = _invoke("one_hot", indices, depth=out.shape[1])
    out._data = res._data
    return out
