"""gluon.nn basic layers.

Parity: `python/mxnet/gluon/nn/basic_layers.py` — Sequential,
HybridSequential, Dense, Dropout, BatchNorm, Embedding, Flatten,
InstanceNorm, LayerNorm, Lambda, HybridLambda.
"""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ...ndarray import NDArray
from ..block import Block, HybridBlock
from .activations import Activation

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "Flatten", "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stacks Blocks sequentially (parity basic_layers.py:33)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({key}): {block}" for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock) for c in self._children.values()):
            import warnings
            warnings.warn(f"All children of this Sequential layer '{self.prefix}' are "
                          "HybridBlocks. Consider using HybridSequential for the best "
                          "performance.", stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks sequentially (parity basic_layers.py:92)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({key}): {block}" for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Densely-connected layer: `activation(dot(x, w.T) + b)`
    (parity basic_layers.py:152; op = FullyConnected → one MXU matmul)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units, in_units),
                                          init=weight_initializer, dtype=dtype,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(units,),
                                            init=bias_initializer, dtype=dtype,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        if self._flatten:
            in_units = int(_np.prod(x.shape[1:]))
        else:
            in_units = x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        act = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten, name="fwd")
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return f"{self.__class__.__name__}({shape[1] if shape[1] else None} -> {shape[0]}, " \
               f"linear)" if self.act is None else \
               f"{self.__class__.__name__}({shape[1] if shape[1] else None} -> {shape[0]}, " \
               f"Activation({self.act._act_type}))"


class Dropout(HybridBlock):
    """Dropout regularization (parity basic_layers.py:226). Only active in
    train mode; keys come from the traced PRNG argument so hybridized
    dropout recompiles zero times across steps."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes, name="fwd",
                             cudnn_off=False)
        return F.identity(x)

    def __repr__(self):
        return f"{self.__class__.__name__}(p = {self._rate}, axes={self._axes})"


class Embedding(HybridBlock):
    """Turns indices into dense vectors (parity basic_layers.py:282;
    op = take → XLA gather)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                          init=weight_initializer, dtype=dtype,
                                          grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._input_dim} -> {self._output_dim}, " \
               f"{self._kwargs['dtype']})"


class BatchNorm(HybridBlock):
    """Batch normalization (parity basic_layers.py:320; reference op
    `src/operator/nn/batch_norm.cc`). Moving stats are aux params updated
    in-place by the op's mutate-aux outputs."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros", running_variance_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale, "use_global_stats": use_global_stats}
        self._axis = axis
        if in_channels != 0:
            self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get("gamma",
                                         grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get("beta",
                                        grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get("running_mean", grad_req="null",
                                                shape=(in_channels,),
                                                init=running_mean_initializer,
                                                allow_deferred_init=True,
                                                differentiable=False)
            self.running_var = self.params.get("running_var", grad_req="null",
                                               shape=(in_channels,),
                                               init=running_variance_initializer,
                                               allow_deferred_init=True,
                                               differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if _np.dtype(dtype).name == "float16":
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var, name="fwd",
                           **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return f"{self.__class__.__name__}(axis={self._axis}, eps={self._kwargs['eps']}, " \
               f"momentum={self._kwargs['momentum']}, " \
               f"fix_gamma={self._kwargs['fix_gamma']}, in_channels={in_channels or None})"


class InstanceNorm(HybridBlock):
    """Instance normalization (parity basic_layers.py:457)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd", eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, name="fwd",
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return f"{self.__class__.__name__}(eps={self._epsilon}, axis={self._axis}, " \
               f"in_channels={in_channels})"


class LayerNorm(HybridBlock):
    """Layer normalization (parity basic_layers.py:538; Ba et al. 2016)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center, "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return f"{self.__class__.__name__}(axis={self._axis}, eps={self._epsilon}, " \
               f"center={self._center}, scale={self._scale}, in_channels={in_channels})"


class Flatten(HybridBlock):
    """Flattens the input to (batch, -1) (parity basic_layers.py:628)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class Lambda(Block):
    """Wraps a callable as a Block (parity basic_layers.py:651)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(nd, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise ValueError("Unrecognized function in lambda: {} of type {}"
                             .format(function, type(function)))
        self._func_name = getattr(self._func_impl, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


class HybridLambda(HybridBlock):
    """Wraps a callable as a HybridBlock (parity basic_layers.py:687)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), f"Function name {function} is not found in ndarray."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise ValueError("Unrecognized function in lambda: {} of type {}"
                             .format(function, type(function)))

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"
