"""gluon.rnn (parity `python/mxnet/gluon/rnn/__init__.py`).

Populated by rnn_cell / rnn_layer as they land (SURVEY.md §7 stage 5).
"""
try:
    from .rnn_cell import *  # noqa: F401,F403
    from .rnn_layer import *  # noqa: F401,F403
    from . import rnn_cell, rnn_layer  # noqa: F401
except ImportError:  # pragma: no cover - during staged build only
    pass
