"""gluon.rnn (parity `python/mxnet/gluon/rnn/__init__.py`)."""
from . import rnn_cell, rnn_layer
from .rnn_cell import *  # noqa: F401,F403
from .rnn_layer import *  # noqa: F401,F403
