"""Fused recurrent layers (parity: `python/mxnet/gluon/rnn/rnn_layer.py`).

RNN/LSTM/GRU over the fused `RNN` op (`ops/rnn.py` — lax.scan recurrence,
MXU-batched input projections). Parameters are registered per
layer/direction (`l0_i2h_weight` …) exactly like the reference so
checkpoints keep the same key set, and concatenated into the flat fused
vector with `_rnn_param_concat` at forward time.
"""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock
from . import rnn_cell

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), f"Invalid layout {layout}"
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        np_ = projection_size if projection_size else nh
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, np_),
                                     h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer)
                if projection_size:
                    self._register_param(f"{j}{i}_h2r_weight", (np_, nh),
                                         h2h_weight_initializer)
            ni = np_ * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        self._reg_params[name] = p
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = f"{shape[1] if shape[1] else None} -> {shape[0] // self._gates}"
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _alias(self):
        # may be called from Block.__init__ before _mode is assigned
        return getattr(self, "_mode", type(self).__name__.lower())

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(**info))
        return states

    def infer_shape(self, x, *args):
        ni = x.shape[-1] if self._layout[-1] == "C" else x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        np_ = self._projection_size if self._projection_size else nh
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                self._reg_params[f"{j}{i}_i2h_weight"].shape = (ng * nh, ni)
            ni = np_ * self._dir

    def __call__(self, inputs, states=None, **kwargs):
        self.skip_states = states is None
        if states is None:
            if isinstance(inputs, nd.NDArray):
                batch_size = inputs.shape[self._layout.find("N")]
                states = self.begin_state(batch_size,
                                          dtype=str(inputs.dtype))
            else:
                states = self.begin_state(0)
        if isinstance(states, nd.NDArray):
            states = [states]
        return super().__call__(inputs, *states, **kwargs)

    def forward(self, x, *args):
        from ...symbol.symbol import Symbol as _Sym

        if isinstance(x, _Sym) or (args and isinstance(args[0], _Sym)):
            return super().forward(x, *args)
        return super().forward(x, *args)

    def hybrid_forward(self, F, inputs, states=None, *extra_states, **params):
        if states is not None and not isinstance(states, (list, tuple)):
            states = [states] + list(extra_states)
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        # flat param vector in the fused op's layout: all weights
        # (layer-major, dir-minor, i2h then h2h), then all biases
        plist = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                plist.append(params[f"{j}{i}_i2h_weight"])
                plist.append(params[f"{j}{i}_h2h_weight"])
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                plist.append(params[f"{j}{i}_i2h_bias"])
                plist.append(params[f"{j}{i}_h2h_bias"])
        if self._projection_size:
            for i in range(self._num_layers):
                for j in ["l", "r"][:self._dir]:
                    plist.append(params[f"{j}{i}_h2r_weight"])
        flat = F._internal._rnn_param_concat(*plist, dim=0)

        if self._mode == "lstm":
            h0, c0 = states
            out = F.RNN(inputs, flat, h0, c0, state_size=self._hidden_size,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._dir == 2, p=self._dropout,
                        projection_size=self._projection_size,
                        state_outputs=True)
            outputs, hT, cT = out
            new_states = [hT, cT]
        else:
            out = F.RNN(inputs, flat, states[0], state_size=self._hidden_size,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._dir == 2, p=self._dropout,
                        state_outputs=True)
            outputs, hT = out
            new_states = [hT]

        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if self.skip_states:
            return outputs
        return outputs, new_states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (reference rnn_layer.py:281)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference rnn_layer.py:383)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", projection_size, **kwargs)

    def state_info(self, batch_size=0):
        # h state carries the projected size for LSTMP (reference
        # rnn_layer.py LSTM.state_info with projection_size)
        hsz = self._projection_size if self._projection_size else self._hidden_size
        return [{"shape": (self._num_layers * self._dir, batch_size, hsz),
                 "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (reference rnn_layer.py:499)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
