"""Recurrent cells (parity: `python/mxnet/gluon/rnn/rnn_cell.py`).

Per-step cells composed the gluon way: each cell is a HybridBlock whose
`__call__(input, states)` advances one step; `unroll` lays the steps out at
trace time so the CachedOp/jit capture compiles the WHOLE unrolled sequence
into one XLA program (the reference unrolls into a symbol graph — same
shape of program, different compiler).
"""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize inputs to a list of per-step tensors or one merged tensor."""
    assert layout in ("NTC", "TNC"), f"unsupported layout {layout}"
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    in_axis = in_layout.find("T") if in_layout else axis
    if isinstance(inputs, nd.NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[in_axis]
            inputs = list(nd.split(inputs, axis=in_axis,
                                   num_outputs=inputs.shape[in_axis],
                                   squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[0]
        if merge is True:
            inputs = [nd.expand_dims(i, axis=axis) for i in inputs]
            inputs = nd.concat(*inputs, dim=axis)
            in_axis = axis
    if isinstance(inputs, nd.NDArray) and axis != in_axis:
        inputs = nd.swapaxes(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis, batch_size


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, nd.NDArray):
        data = nd.stack(*data, axis=time_axis)
    outputs = nd.SequenceMask(data, sequence_length=valid_length,
                              use_sequence_length=True, axis=time_axis)
    if not merge:
        outputs = list(nd.split(outputs, num_outputs=outputs.shape[time_axis],
                                axis=time_axis, squeeze_axis=True))
    return outputs


class RecurrentCell(Block):
    """Base class for recurrent cells (reference rnn_cell.py:60)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    @property
    def _curr_prefix(self):
        return f"{self.prefix}t{self._counter}_"

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called directly."
        states = []
        func = func or nd.zeros
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name=f"{self._prefix}begin_state_{self._init_counter}",
                         **info) if "name" in _fn_params(func) else func(**info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell for `length` steps (reference rnn_cell.py:305)."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        begin_state = _get_begin_state(self, nd, begin_state, inputs, batch_size)

        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*ele_list, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(nd, outputs, length,
                                                     valid_length, axis, True)
        if merge_outputs:
            outputs = [nd.expand_dims(o, axis=axis) for o in outputs]
            outputs = nd.concat(*outputs, dim=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


def _fn_params(fn):
    import inspect
    try:
        return inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return {}


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Recurrent cells implementing hybrid_forward."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_i x + b_i + W_h h + b_h)
    (reference rnn_cell.py:345)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size, name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size, name=prefix + "h2h")
        i2h_plus_h2h = F.elemwise_add(i2h, h2h, name=prefix + "plus0")
        output = self._get_activation(F, i2h_plus_h2h, self._activation,
                                      name=prefix + "out")
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gate order [i, f, g, o] (reference rnn_cell.py:447,
    matching the fused RNN op's cuDNN layout)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh", recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        self.i2h_weight = self.params.get("i2h_weight", shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = F.elemwise_add(i2h, h2h, name=prefix + "plus0")
        slice_gates = F.SliceChannel(gates, num_outputs=4,
                                     name=prefix + "slice")
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation, name=prefix + "i")
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation, name=prefix + "f")
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation, name=prefix + "c")
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation, name=prefix + "o")
        next_c = F.elemwise_add(
            F.elemwise_mul(forget_gate, states[1], name=prefix + "mul0"),
            F.elemwise_mul(in_gate, in_transform, name=prefix + "mul1"),
            name=prefix + "state")
        next_h = F.elemwise_mul(
            out_gate, self._get_activation(F, next_c, self._activation),
            name=prefix + "out")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, gate order [r, z, n] (reference rnn_cell.py:599)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight", shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "h2h")
        i2h_r, i2h_z, i2h = F.SliceChannel(i2h, num_outputs=3,
                                           name=prefix + "i2h_slice")
        h2h_r, h2h_z, h2h = F.SliceChannel(h2h, num_outputs=3,
                                           name=prefix + "h2h_slice")
        reset_gate = F.Activation(F.elemwise_add(i2h_r, h2h_r), act_type="sigmoid",
                                  name=prefix + "r_act")
        update_gate = F.Activation(F.elemwise_add(i2h_z, h2h_z), act_type="sigmoid",
                                   name=prefix + "z_act")
        next_h_tmp = F.Activation(
            F.elemwise_add(i2h, F.elemwise_mul(reset_gate, h2h)),
            act_type="tanh", name=prefix + "h_act")
        ones = F.ones_like(update_gate, name=prefix + "ones")
        next_h = F.elemwise_add(
            F.elemwise_mul(F.elemwise_sub(ones, update_gate), next_h_tmp),
            F.elemwise_mul(update_gate, prev_state_h), name=prefix + "out")
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells sequentially (reference rnn_cell.py:690)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            inputs, state = cell(inputs, states[p:p + n])
            p += n
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, _, batch_size = _format_sequence(length, inputs, layout, None)
        num_cells = len(self._children)
        begin_state = _get_begin_state(self, nd, begin_state, inputs, batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class HybridSequentialRNNCell(SequentialRNNCell):
    pass


class DropoutCell(HybridRecurrentCell):
    """Apply dropout on input (reference rnn_cell.py:789)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes,
                               name=f"t{self._counter}_fwd")
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that modify another cell (reference rnn_cell.py:841)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference rnn_cell.py:896)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        super().__init__(base_cell)
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: F.Dropout(F.ones_like(like), p=p)
                if p > 0 else None)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        p_outputs = self._zoneout_outputs
        m_out = mask(p_outputs, next_output)
        output = F.where(m_out, next_output, prev_output) \
            if m_out is not None else next_output
        p_states = self._zoneout_states
        if p_states > 0:
            new_states = []
            for new_s, old_s in zip(next_states, states):
                m = mask(p_states, new_s)
                new_states.append(F.where(m, new_s, old_s))
            states = new_states
        else:
            states = next_states
        self._prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Add residual connection around a cell (reference rnn_cell.py:964)."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = F.elemwise_add(output, inputs,
                                name=f"t{self._counter}_fwd")
        return output, states

    def _alias(self):
        return "residual"

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, nd.NDArray) \
            if merge_outputs is None else merge_outputs
        inputs, axis, _ = _format_sequence(length, inputs, layout, merge_outputs)
        if valid_length is not None:
            inputs = _mask_sequence_variable_length(nd, inputs, length,
                                                    valid_length, axis,
                                                    merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [o + i for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells in opposite directions (reference rnn_cell.py:1030)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cells cannot be stepped; "
                                  "use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        reversed_inputs = list(reversed(inputs))
        begin_state = _get_begin_state(self, nd, begin_state, inputs, batch_size)

        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info(batch_size))],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info(batch_size)):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            r_outputs = _mask_sequence_variable_length(
                nd, list(reversed(r_outputs)), length, valid_length, axis, False)
        else:
            r_outputs = list(reversed(r_outputs))
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = [nd.expand_dims(o, axis=axis) for o in outputs]
            outputs = nd.concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states
