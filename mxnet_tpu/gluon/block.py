"""gluon.Block / HybridBlock — the neural-network container API.

Parity: `python/mxnet/gluon/block.py` (`Block`:127 — children/params/
name-scope/`__call__`:535; `HybridBlock`:671 — `_build_cache`:748 creating an
`ndarray.CachedOp`:785, `hybridize`:832, deferred shape inference).

TPU-native redesign: hybridize does NOT lower to a Symbol graph — the same
eager NDArray code is traced by `jax.jit` into one XLA program (see
`mxnet_tpu._cached_op.CachedOp`). Deferred parameter-shape inference runs
the forward under `jax.eval_shape` (abstract evaluation — zero FLOPs), the
analogue of the reference's symbolic `infer_shape` pass
(`infer_graph_attr_pass.cc:94`).
"""
from __future__ import annotations

import copy
import re
import threading

import numpy as _np
import jax

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import name as _name
from .._cached_op import CachedOp
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name-manager scope for Blocks (parity block.py:35)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                if not hasattr(_name.NameManager._current, "value"):
                    _name.NameManager._current.value = _name.NameManager()
                prefix = _name.NameManager._current.value.get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        self._name_scope = _name.Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


def _flatten(args, inout_str):
    """Flatten nested list/tuple structure of NDArrays (parity block.py:57)."""
    if isinstance(args, NDArray):
        return [args], int(0)
    if args is None:
        return [None], int(-1)
    assert isinstance(args, (list, tuple)), \
        f"{inout_str} must be (nested) list of NDArray, but got {type(args)}"
    flat = []
    fmts = []
    for i in args:
        arg, fmt = _flatten(i, inout_str)
        flat.extend(arg)
        fmts.append(fmt)
    return flat, fmts


def _regroup(args, fmt):
    if isinstance(fmt, int):
        if fmt == -1:
            return None, args
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block:
    """Base class for all neural network layers and models
    (parity `gluon/block.py:127`)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = {}
        self._forward_pre_hooks = {}

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({key}): {_indent(repr(block), 2)}"
                           for key, block in self.__dict__.items()
                           if isinstance(block, Block))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(value, type(existing)):
                raise TypeError(f"Changing attribute type for {self.name} from "
                                f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or self._reg_params[name] is value, \
                f"Overriding Parameter attribute {name} is not allowed. " \
                f"If you want to share parameters between blocks, please set " \
                f"'params' at Block construction instead."
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        """This block's direct ParameterDict (no children)."""
        return self._params

    def collect_params(self, select=None):
        """Return a ParameterDict with this block's and all children's
        Parameters, optionally filtered by regex ``select``.

        Direct Parameter attributes (``self.w = Parameter(...)``) are
        included under ``"<block_name>.<attr>"`` keys and fully support
        imperative training, ``initialize`` and ``save_parameters`` /
        ``load_parameters`` (which key by attribute path). They are NOT
        visible to the 1.x symbolic surfaces — ``HybridBlock.export`` and
        prefix-keyed ``ParameterDict.save/load`` — which match the
        ParameterDict-created prefixed names; use ``self.params.get``
        for parameters that must round-trip through symbol JSON.

        The result is IDENTITY-deduplicated: a Parameter shared across
        blocks (tied weights held as a direct attribute on two blocks)
        appears exactly once, under its first-encountered key — two keys
        for one Parameter would register it twice in ``Trainer``, which
        then double-applies its update with two separate optimizer slots
        (the reference's name-keyed ParameterDict dedupes tied params
        naturally)."""
        self._check_container_with_block()
        ret = ParameterDict(self._params.prefix)
        seen = set()

        def merge(items):
            fresh = {}
            for name, p in items:
                if id(p) in seen:
                    continue
                seen.add(id(p))
                fresh[name] = p
            ret.update(fresh)

        # direct Parameter ATTRIBUTES (2.x style: `self.w = Parameter(...)`)
        # live in _reg_params only; without this they would be saved by
        # save_parameters (which walks _reg_params) yet invisible to
        # initialize()/Trainer — silently untrained parameters. Keyed by
        # the block's unique instance name (user-chosen Parameter names
        # like "weight" repeat across sibling layers).
        lib_params = set(map(id, self.params.values()))
        direct = {f"{self.name}.{attr}": p
                  for attr, p in self._reg_params.items()
                  if id(p) not in lib_params}
        if not select:
            merge(self.params.items())
            merge(direct.items())
        else:
            pattern = re.compile(select)
            merge((name, value) for name, value in self.params.items()
                  if pattern.match(name))
            merge((name, value) for name, value in direct.items()
                  if pattern.match(name))
        for cld in self._children.values():
            merge(cld.collect_params(select=select).items())
        return ret

    def _check_container_with_block(self):
        children = set(self._children.values())
        for k, v in self.__dict__.items():
            if isinstance(v, (list, tuple, dict)) and not k.startswith("_"):
                items = v.values() if isinstance(v, dict) else v
                for item in items:
                    if isinstance(item, Block) and item not in children:
                        import warnings
                        warnings.warn(f'"{item}" is an unregistered container with Blocks. '
                                      f"Note that Blocks inside the list, tuple or dict will "
                                      f"not be registered automatically. Make sure to register "
                                      f"them using register_child() or switching to "
                                      f"nn.Sequential/nn.HybridSequential instead.")

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle.id] = hook
        return handle

    def apply(self, fn):
        """Apply ``fn`` recursively to every child then self."""
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        from .. import initializer
        if init is None:
            init = initializer.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def save_parameters(self, filename, deduplicate=False):
        """Save parameters to file (reference `block.py save_parameters`;
        format = NDArray-dict `.params`, `ndarray.cc:1578`)."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val._reduce() if hasattr(val, "_reduce")
                    else val.data(val.list_ctx()[0]).copyto(cpu())
                    for key, val in params.items()}
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in i for i in loaded.keys()):
            # legacy loading: use full-name ParameterDict load
            del loaded
            self.collect_params().load(filename, ctx, allow_missing, ignore_extra,
                                       self.prefix)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}', which contains " \
                    f"parameters: {_brief_print_list(loaded.keys())}. Set allow_missing=True " \
                    f"to ignore missing parameters."
        for name in loaded:
            if not ignore_extra and name not in params:
                raise ValueError(
                    f"Parameter '{name}' loaded from file '{filename}' is not present in "
                    f"ParameterDict, which contains parameters "
                    f"{_brief_print_list(params.keys())}. Set ignore_extra=True to ignore.")
            if name in params:
                params[name]._load_init(loaded[name], ctx)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # MXNet<=1.3 names kept as aliases
    save_params = save_parameters
    load_params = load_parameters

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        """Override to implement forward computation using NDArray."""
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a table of layers/params (parity block.py summary)."""
        summary = []
        hooks = []

        def _register(block):
            def hook(blk, inp, out):
                n_params = sum(int(_np.prod(p.shape)) for p in blk._reg_params.values()
                               if p.shape is not None)
                out0 = out[0] if isinstance(out, (list, tuple)) else out
                summary.append((blk.name, type(blk).__name__,
                                getattr(out0, "shape", None), n_params))
            hooks.append(block.register_forward_hook(hook))

        self.apply(_register)
        try:
            self(*inputs)
            print(f"{'Layer (type)':<44}{'Output Shape':<24}{'Param #':<12}")
            print("=" * 80)
            total = 0
            for name, cls, shape, n in summary:
                print(f"{name + ' (' + cls + ')':<44}{str(shape):<24}{n:<12}")
                total += n
            print("=" * 80)
            print(f"Total params: {total}")
        finally:
            for h in hooks:
                h.detach()


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks_dict):
        self._hooks_dict = hooks_dict
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1

    def detach(self):
        self._hooks_dict.pop(self.id, None)


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    if len(lines) == 1:
        return s_
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


def _brief_print_list(lst, limit=7):
    lst = list(lst)
    if len(lst) > limit:
        return ", ".join(map(repr, lst[:limit // 2])) + ", ..., " + \
            ", ".join(map(repr, lst[-limit // 2:]))
    return ", ".join(map(repr, lst))


class HybridBlock(Block):
    """A Block that can be captured into a single compiled XLA program.

    Parity: `gluon/block.py:671`. ``hybrid_forward(self, F, x, *args,
    **params)`` receives ``F = mxnet_tpu.ndarray`` in BOTH modes — there is
    no separate symbol tracing language; hybridization is jax tracing of the
    identical code (SURVEY.md §7 stage 3).
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._cached_op = None
        self._active = False
        self._flags = {}
        self._in_fmt = None

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, (HybridBlock, Parameter)):
            self._clear_cached_op()

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                f"Children of HybridBlock must also be HybridBlock, but {str(block)} has "
                f"type {str(type(block))}. If you are using Sequential, please try "
                f"HybridSequential instead.")
        super().register_child(block, name)
        self._clear_cached_op()

    def hybridize(self, active=True, static_alloc=False, static_shape=False):
        self._active = active
        self._flags = {"static_alloc": static_alloc, "static_shape": static_shape}
        self._clear_cached_op()
        super().hybridize(active, static_alloc=static_alloc, static_shape=static_shape)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def _clear_cached_op(self):
        self._cached_op = None

    # -- deferred shape inference ------------------------------------------

    def infer_shape(self, *args):
        """Infer (and set) deferred parameter shapes from input shapes.

        Leaf layers with deferred params (Dense, Conv, norms) override this
        to set shapes directly from the input. The generic version runs the
        whole subtree's forward under ``jax.eval_shape`` (abstract
        evaluation, zero FLOPs): each leaf hit mid-trace catches its own
        DeferredInitializationError and resolves itself from its (shaped)
        tracer inputs. This replaces the reference's symbolic InferShape
        pass (`infer_graph_attr_pass.cc:94`) with the compiler's own
        abstract interpreter."""
        self._generic_infer_shape(*args)

    def infer_type(self, *args):
        self._generic_infer_shape(*args)

    def _generic_infer_shape(self, *args):
        from .. import autograd
        if getattr(self, "_in_shape_inference", False):
            raise NotImplementedError(
                f"{type(self).__name__} has uninitialized parameters with unknown shape "
                f"and does not override `infer_shape`. Construct it with fully-specified "
                f"shapes (in_units/in_channels) or implement `infer_shape`.")
        self._in_shape_inference = True
        try:
            from .. import random as _random
            flat, fmt = _flatten(args, "input")
            avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) if isinstance(a, NDArray) else a
                     for a in flat]
            # concrete base key fetched OUTSIDE the abstract trace (a key
            # minted inside eval_shape would be a tracer and poison the
            # process-global eager provider)
            base_key = _random.next_key()

            def run(*tracers):
                nds = [NDArray(t) if not isinstance(t, NDArray) else t for t in tracers]
                re_args, _ = _regroup(list(nds), fmt)
                if not isinstance(re_args, (list, tuple)):
                    re_args = [re_args]
                # empty (non-None) override map forces the eager code path in
                # every nested hybridized block without providing values; the
                # trace key provider keeps abstract keys out of the eager PRNG
                token = _PARAM_OVERRIDE.set({})
                token2 = _SHAPE_INFER.set(True)
                try:
                    with autograd._RecordingStateScope(False, None):
                        with _random.TraceKeyProvider(base_key):
                            out = self.forward(*re_args)
                finally:
                    _SHAPE_INFER.reset(token2)
                    _PARAM_OVERRIDE.reset(token)
                flat_out, _ = _flatten(out, "output")
                return [o._data for o in flat_out]

            jax.eval_shape(run, *avals)
            # shapes are now known everywhere; materialize OUTSIDE the trace
            for p in self.collect_params().values():
                if p._deferred_init:
                    p._finish_deferred_init()
        finally:
            self._in_shape_inference = False

    # -- forward ------------------------------------------------------------

    def _build_cache(self):
        """Create the CachedOp: params are leading inputs, then data
        (reference `_build_cache` block.py:748)."""
        params = self._cached_graph_params = list(self.collect_params().values())

        def fn(*arrays):
            n = len(params)
            param_arrays, inputs = arrays[:n], arrays[n:]
            # bind traced param values into the blocks for the duration of
            # the trace via a value override
            overrides = {id(p): a for p, a in zip(params, param_arrays)}
            token = _PARAM_OVERRIDE.set(overrides)
            try:
                args, _ = _regroup(list(inputs), self._in_fmt)
                if not isinstance(args, (list, tuple)):
                    args = [args]
                out = self.hybrid_forward_dispatch(*args)
            finally:
                _PARAM_OVERRIDE.reset(token)
            flat, self._out_fmt = _flatten(out, "output")
            return flat

        self._cached_op = CachedOp(fn, **self._flags)

    def hybrid_forward_dispatch(self, *args):
        """Run this block's forward with params fetched (possibly traced)."""
        return self.forward(*args)

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            flat_args, self._in_fmt = _flatten(args, "input")
            self._build_cache()
        else:
            flat_args, fmt = _flatten(args, "input")
            if fmt != self._in_fmt:
                self._in_fmt = fmt
                self._build_cache()
                flat_args, _ = _flatten(args, "input")
        params = self._cached_graph_params
        try:
            param_nds = [p.data() for p in params]
        except DeferredInitializationError:
            self._deferred_infer_shape(*args)
            for p in params:
                if p._deferred_init:
                    p._finish_deferred_init()
            param_nds = [p.data() for p in params]
        out = self._cached_op(*(param_nds + list(flat_args)))
        if isinstance(out, NDArray):
            out = [out]
        ret, _ = _regroup(list(out), self._out_fmt)
        return ret

    def _deferred_infer_shape(self, *args):
        try:
            self.infer_shape(*args)
        except Exception as e:
            error_msg = f"Deferred initialization failed because shape cannot be " \
                        f"inferred. {e}"
            raise ValueError(error_msg) from e

    def __call__(self, *args):
        return super().__call__(*args)

    def forward(self, x, *args):
        """Defines the forward computation; calls hybrid_forward with
        ``F = mxnet_tpu.ndarray`` (NDArray inputs) or ``F =
        mxnet_tpu.symbol`` (Symbol inputs — the reference's symbolic
        hybridization path, used by `export`)."""
        from ..symbol.symbol import Symbol as _Sym

        if isinstance(x, _Sym):
            from .. import symbol as _sym_api

            params = {k: v.var() for k, v in self._reg_params.items()}
            return self.hybrid_forward(_sym_api, x, *args, **params)
        if self._active and _PARAM_OVERRIDE.get() is None:
            return self._call_cached_op(x, *args)
        try:
            params = {k: _param_value(v) for k, v in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            if not _SHAPE_INFER.get():
                # real (non-abstract) call: materialize now
                for p in self._reg_params.values():
                    if p._deferred_init:
                        p._finish_deferred_init()
            params = {k: _param_value(v) for k, v in self._reg_params.items()}
        return self.hybrid_forward(nd, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Override to implement forward computation using NDArray ops via F."""
        raise NotImplementedError

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Export `path-symbol.json` + `path-####.params` for deployment
        (reference block.py HybridBlock.export): the forward is re-traced
        SYMBOLICALLY (F=symbol) so the emitted json round-trips through
        `SymbolBlock.imports` and the Module checkpoint loader."""
        from .. import symbol as _sym_api

        n_in = len(self._in_fmt) if isinstance(getattr(self, "_in_fmt", None),
                                               (list, tuple)) else 1
        if n_in == 1:
            data_syms = [_sym_api.var("data")]
        else:
            data_syms = [_sym_api.var(f"data{i}") for i in range(n_in)]
        out = self(*data_syms)
        if not isinstance(out, (list, tuple)):
            out = [out]
        sym = _sym_api.Group(list(out)) if len(out) > 1 else out[0]
        sym.save(f"{path}-symbol.json", remove_amp_cast=remove_amp_cast)

        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        arg_dict = {}
        for name, param in self.collect_params().items():
            if name in arg_names:
                arg_dict[f"arg:{name}"] = param._reduce() if hasattr(param, "_reduce") \
                    else param.data(param.list_ctx()[0]).copyto(cpu())
            elif name in aux_names:
                arg_dict[f"aux:{name}"] = param.data(param.list_ctx()[0]).copyto(cpu())
        fname = f"{path}-{epoch:04d}.params"
        nd.save(fname, arg_dict)
        return sym
        return fname


# During CachedOp tracing, Parameter.data() values are overridden with
# tracer-backed NDArrays; contextvar maps id(Parameter) -> jax value.
import contextvars

_PARAM_OVERRIDE = contextvars.ContextVar("mxnet_tpu_param_override", default=None)
# True while the shape-only abstract pass runs: params must NOT materialize
# inside the trace (a buffer created there would be a leaked tracer)
_SHAPE_INFER = contextvars.ContextVar("mxnet_tpu_shape_infer", default=False)


def _param_value(p):
    overrides = _PARAM_OVERRIDE.get()
    if overrides is not None and id(p) in overrides:
        v = overrides[id(p)]
        return v if isinstance(v, NDArray) else NDArray(v)
    if _SHAPE_INFER.get() and p._data is None:
        from .parameter import _shape_complete
        if _shape_complete(p.shape):
            import jax.numpy as jnp
            # abstract stand-in: shape/dtype only, value never escapes
            return NDArray(jnp.zeros(p.shape, p.dtype))
    return p.data()


class SymbolBlock(HybridBlock):
    """A Block wrapping a pre-built Symbol graph (reference `block.py:952`):
    the deserialization target of `HybridBlock.export` /
    `model.save_checkpoint`. Parameters are the symbol's non-input
    arguments; the graph executes as one jitted program through the same
    machinery as the symbolic Executor."""

    def __init__(self, outputs, inputs, params=None):
        from ..symbol.symbol import Symbol, Group

        # bypass HybridBlock prefix machinery: param names must match the
        # symbol's argument names exactly
        super().__init__(prefix="", params=None)
        self._params = ParameterDict("", shared=params)

        if isinstance(inputs, Symbol):
            inputs = list(inputs) if len(inputs) > 1 else [inputs]
        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs)) if len(outputs) > 1 else outputs[0]
        self._sym = outputs
        self._input_names = [i.name for i in inputs]

        arg_names = self._sym.list_arguments()
        aux_names = set(self._sym.list_auxiliary_states())
        self._param_order = []
        for name in arg_names + sorted(aux_names):
            if name in self._input_names:
                continue
            grad_req = "null" if name in aux_names else "write"
            p = self.params.get(name, grad_req=grad_req,
                                allow_deferred_init=True)
            self._reg_params[name] = p
            self._param_order.append(name)
        self._graph_fns = {}

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None,
                allow_missing=False, ignore_extra=False):
        """Load an exported model: `SymbolBlock.imports('m-symbol.json',
        ['data'], 'm-0000.params')` (reference block.py SymbolBlock.imports)."""
        from .. import symbol as _sym_api

        sym = _sym_api.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_sym_api.var(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.collect_params().load(param_file, ctx=ctx,
                                      allow_missing=allow_missing,
                                      ignore_extra=ignore_extra)
        return ret

    def _sb_fn(self, train):
        fn = self._graph_fns.get(train)
        if fn is None:
            from ..symbol.executor import _graph_fn

            aux = self._sym.list_auxiliary_states()
            args = [n for n in self._input_names +
                    [p for p in self._param_order if p not in aux]]
            # _graph_fn wants arg order = the order we pass arrays in
            fn = _graph_fn(self._sym, args, aux, train)
            self._graph_fns[train] = fn
        return fn

    def forward(self, x, *args):
        from ..symbol.symbol import Symbol as _Sym
        from .. import random as _random
        from .. import autograd as _ag

        if isinstance(x, _Sym):
            raise MXNetError("SymbolBlock cannot be re-traced symbolically")
        inputs = [x] + [a for a in args if a is not None]
        if len(inputs) != len(self._input_names):
            raise MXNetError(f"SymbolBlock expects {len(self._input_names)} "
                             f"inputs {self._input_names}, got {len(inputs)}")
        # finish deferred param init from input shapes
        try:
            for name in self._param_order:
                self._reg_params[name].data()
        except DeferredInitializationError:
            shapes = {n: tuple(i.shape) for n, i in zip(self._input_names, inputs)}
            arg_shapes, _, aux_shapes = self._sym.infer_shape_partial(**shapes)
            arg_names = self._sym.list_arguments()
            aux_names = self._sym.list_auxiliary_states()
            for n, s in list(zip(arg_names, arg_shapes)) + list(zip(aux_names, aux_shapes)):
                if n in self._reg_params and s is not None:
                    p = self._reg_params[n]
                    if p._data is None:
                        p.shape = s
                        if p._deferred_init:
                            p._finish_deferred_init()
                        else:
                            p.initialize()
        aux_set = set(self._sym.list_auxiliary_states())
        train = bool(_ag.is_training())
        fn = self._sb_fn(train)
        key = _random.next_key()
        arg_arrays = tuple(i._data for i in inputs) + tuple(
            self._reg_params[n].data()._data for n in self._param_order
            if n not in aux_set)
        aux_arrays = tuple(self._reg_params[n].data()._data
                           for n in self._sym.list_auxiliary_states())
        outs, aux_new = fn(key, arg_arrays, aux_arrays)
        if train:
            for n, a in zip(self._sym.list_auxiliary_states(), aux_new):
                self._reg_params[n].data()._data = a
        out_nds = [NDArray(o) for o in outs]
        return out_nds[0] if len(out_nds) == 1 else out_nds
