"""gluon.Trainer — applies an Optimizer to a set of Parameters.

Parity: `python/mxnet/gluon/trainer.py:27` (`_init_kvstore`:169,
`step`:298, `allreduce_grads`:327, `update`:359) and the kvstore wiring
helper `python/mxnet/model.py:82 _create_kvstore`.

TPU-native notes: for single-process multi-device the grads are reduced by
the local kvstore (one fused XLA reduction per parameter); for multi-host
the 'dist_tpu_sync' kvstore allreduces over ICI/DCN — `update_on_kvstore`
is forced False there (no server processes exist; the reference's
server-side optimizer `kvstore_dist_server.h:346` maps to
allreduce-then-local-update, the Horovod-style flow the reference itself
uses at `gluon/trainer.py:327`).

ZeRO-1 (`MXNET_ZERO1=1`): the aggregated updater call `step()` makes per
context rides `Updater._zero1_call` — the optimizer state lives dp-SHARDED
in flat buckets (1/N per replica, `parallel/zero1.py`) and the update runs
on each replica's shard, allgathered back into the full weights.
`save_states`/`load_states` stay transparent: the updater gathers shards
into ordinary per-parameter states before pickling and re-shards on load.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        param_list = []
        if isinstance(params, (dict, ParameterDict)):
            for key in sorted(list(params.keys())):
                param_list.append(params[key])
            params = param_list
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            # keyed by identity: Parameter NAMES may repeat across sibling
            # blocks (2.x-style direct attributes, e.g. two "weight"s) and a
            # name-keyed table would silently collapse two params onto one
            # kvstore slot in multi-context/dist runs
            if id(param) in self._param2idx:
                # the SAME Parameter passed twice (tied weights collected
                # under two keys, or a duplicated list): register once — a
                # second slot would double-apply its update and warn about
                # a stale gradient on the first step
                continue
            self._param2idx[id(param)] = len(self._params)
            self._params.append(param)
            param._set_trainer(self)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._distributed = None
        self._params_to_init = []
        self._reset_kvstore()

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                f"All Parameters must be initialized on the same set of contexts, " \
                f"but Parameter {param.name} is initialized on {str(ctx)} while previous " \
                f"Parameters are initialized on {str(contexts)}."
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _reset_kvstore(self):
        if self._kvstore and "dist" in self._kvstore.type:
            raise RuntimeError("Cannot reset distributed KVStore.")
        self._kv_initialized = False
        self._kvstore = None
        self._distributed = None
        self._update_on_kvstore = None
        self._grad_sync = None  # bucketed sync scheduler (lazy, per store)
        self._params_to_init = [param for param in self._params]

    def _init_kvstore(self):
        """Create kvstore and set update-on-kvstore (parity trainer.py:169)."""
        config = self._kvstore_params
        arg_arrays = {f"{i}_{param.name}": param.data(self._contexts[0])
                      for i, param in enumerate(self._params)}
        kvstore, update_on_kvstore = _create_kvstore(
            config["kvstore"], len(self._contexts), arg_arrays)
        self._distributed = "dist" in kvstore.type if kvstore else False
        if self._distributed:
            # allreduce-over-ICI has no server; update locally after sync
            update_on_kvstore = False
        if any(p._grad_stype == "row_sparse" for p in self._params):
            # sparse grads aggregate through the sparse merge path and update
            # locally (reference trainer.py:169: update_on_kvstore=False when
            # grads are sparse but weights dense)
            update_on_kvstore = False
        if config["update_on_kvstore"] is not None:
            update_on_kvstore = config["update_on_kvstore"]
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    def _init_params(self):
        """Push uninitialized-on-kv params into the kvstore."""
        assert self._kv_initialized, \
            "Cannot initialize parameters in KVStore when KVStore is not initialized."
        params_to_init = []
        if self._kvstore:
            for param in self._params_to_init:
                if param._deferred_init:
                    params_to_init.append(param)
                else:
                    param_arrays = param._check_and_get(param._data, list)
                    idx = self._param2idx[id(param)]
                    self._kvstore.init(idx, param_arrays[0])
                    if param._stype == "default" and self._update_on_kvstore:
                        self._kvstore.pull(idx, param_arrays, priority=-idx)
        self._params_to_init = params_to_init

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate can be accessed.")
        return self._optimizer.learning_rate if hasattr(self._optimizer, "learning_rate") \
            else self._optimizer.lr

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate is mutated.")
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Make one parameter-update step: rescale by 1/batch_size, allreduce
        grads, update (parity trainer.py:298)."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _check_and_rescale_grad(self, scale):
        if self._update_on_kvstore and self._distributed and self._kv_initialized:
            if self._optimizer.rescale_grad != scale:
                raise UserWarning("Possible change in the `batch_size` from previous "
                                  "`step` detected. Optimizer gradient normalizing "
                                  "factor will not change w.r.t new batch_size when "
                                  "update_on_kvstore=True")
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        """Reduce gradients over devices/workers WITHOUT updating — for
        gradient manipulation between backward and update
        (parity trainer.py:327)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "allreduce_grads() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` " \
            "to False when creating trainer."
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Bucketed by default (`parallel/grad_sync.py`): dense grads ride
        O(#buckets) flat collectives — issued asynchronously in gradient
        readiness order, drained in priority order — instead of one
        push(+pull) per parameter. `MXNET_GRAD_BUCKETING=0` restores the
        per-key reference loop."""
        if not self._kvstore:
            return
        from ..parallel import grad_sync as _gs

        # compressed stores keep the per-key push (quantization + error
        # feedback live inside push); grouped update_on_kvstore pushes
        # still compress per key, so only the flat-allreduce path gates
        bucketed = _gs.bucketing_enabled() and (
            self._update_on_kvstore or _gs.sync_compatible(self._kvstore))
        dense = []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._grad_stype == "row_sparse":
                # row_sparse grads never ride the dense push/pull (which
                # would densify the table): merge sparse pieces directly
                self._allreduce_sparse_grads(i, param)
                continue
            if bucketed:
                dense.append((i, param.list_grad()))
                continue
            self._kvstore.push(i, param.list_grad(), priority=-i)
            if not self._update_on_kvstore:
                self._kvstore.pull(i, param.list_grad(), priority=-i,
                                   ignore_sparse=self._distributed)
        if dense:
            grads = [g for _, g in dense]
            prios = [-i for i, _ in dense]
            if self._update_on_kvstore:
                # optimizer lives on the store: one grouped push (the store
                # buckets the keys), weights come back in `_update`'s pull
                self._kvstore.push([i for i, _ in dense], grads,
                                   priority=prios)
            else:
                if self._grad_sync is None:
                    self._grad_sync = _gs.GradSync(self._kvstore)
                self._grad_sync.configure_from(grads, priorities=prios)
                self._grad_sync.sync(grads)

    def _allreduce_sparse_grads(self, i, param):
        """Aggregate row_sparse grads across device replicas (and worker
        processes for dist) while staying O(touched rows) — the role of the
        reference's row_sparse CommCPU reduce (`comm.h` ReduceRowSparse) +
        ps-lite row_sparse push (`kvstore_dist.h:676`)."""
        import jax.numpy as jnp
        from .. import autograd
        from ..ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray

        grads = [g for g in param.list_grad() if isinstance(g, RowSparseNDArray)]
        if not grads:
            return
        idx = jnp.concatenate([g.indices._data.astype(jnp.int32) for g in grads])
        data = jnp.concatenate([g.data._data for g in grads])
        if self._distributed:
            # one padded all-gather of the occupied rows over the workers
            merged_local = RowSparseNDArray(
                NDArray(data), NDArray(idx), tuple(grads[0].shape))
            self._kvstore.push(i, merged_local, priority=-i)
            uniq, summed = self._kvstore.pull_sparse_grad(i)
        else:
            ct = autograd._RowSparseCT(idx, data, tuple(grads[0].shape),
                                       grads[0].dtype)
            uniq, summed = ct.dedup()
        for g in grads:
            g._aux = {"data": NDArray(jnp.asarray(summed, g.dtype)),
                      "indices": NDArray(uniq)}
            g._dense_cache = None
            g._aux_stale = False

    def update(self, batch_size, ignore_stale_grad=False):
        """Update parameters WITHOUT allreduce — second half of the split
        step (parity trainer.py:359)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` " \
            "to False when creating trainer."
        self._check_and_rescale_grad(self._scale / batch_size)
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updates = [[] for _ in self._updaters]

        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not ignore_stale_grad:
                for data in param._check_and_get(param._data, list):
                    if not data._fresh_grad:
                        raise UserWarning(
                            f"Gradient of Parameter `{param.name}` on context "
                            f"{str(data.context)} has not been updated by backward "
                            f"since last `step`. This could mean a bug in your model "
                            f"that made it only use a subset of the Parameters (Blocks) "
                            f"for this iteration. If you are intentionally only using "
                            f"a subset, call step with ignore_stale_grad=True to "
                            f"suppress this warning")
            if self._kvstore and self._update_on_kvstore:
                # optimizer ran on the kvstore; fetch the updated weights
                # (reference trainer.py:411-415)
                if param._stype == "default":
                    self._kvstore.pull(i, param.list_data(), priority=-i)
                continue
            for upd, arr, grad in zip(updates, param.list_data(), param.list_grad()):
                if not ignore_stale_grad or arr._fresh_grad:
                    upd.append((i, grad, arr))
                    arr._fresh_grad = False

        if not (self._kvstore and self._update_on_kvstore):
            for updater, upd in zip(self._updaters, updates):
                if upd:
                    i, g, w = zip(*upd)
                    updater(list(i), list(g), list(w))

    def _row_sparse_pull(self, parameter, row_id, full_idx=False):
        """Refresh the requested rows of a sparse parameter from the kvstore
        (parity trainer.py:289 `_row_sparse_pull`).

        Only meaningful when the optimizer runs ON the kvstore (the store
        then holds the authority copy, like the reference's servers); with
        local updates — the TPU dist default — every worker's weight is
        already authoritative and this is a no-op."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._kvstore is None or not self._update_on_kvstore:
            return
        import jax.numpy as jnp
        from ..ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray

        idx = self._param2idx[id(parameter)]
        w = parameter._check_and_get(parameter._data, None)
        # a row_sparse out makes the store hand back only (indices, rows)
        tmp = RowSparseNDArray(
            NDArray(jnp.zeros((0,) + tuple(w.shape[1:]), w.dtype)),
            NDArray(jnp.zeros((0,), jnp.int32)), tuple(w.shape))
        self._kvstore.row_sparse_pull(idx, out=tmp, row_ids=row_id, priority=-idx)
        rows = tmp.indices._data.astype(jnp.int32)
        if rows.size:
            w._data = w._data.at[rows].set(tmp.data._data.astype(w.dtype))

    def save_states(self, fname):
        """Save optimizer (updater) states (parity trainer.py:419)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            assert not self._params_to_init, "Cannot save trainer states when some " \
                                             "parameters are not yet initialized in kvstore."
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Load optimizer (updater) states (parity trainer.py:451)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
