"""gluon — the imperative/hybrid neural-network API.

Parity: `python/mxnet/gluon/__init__.py`.
"""
from . import parameter
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError

from . import block
from .block import Block, HybridBlock, SymbolBlock

from . import nn
from . import loss
from . import utils
from . import trainer
from .trainer import Trainer

from . import rnn
from . import data
from . import model_zoo
from . import contrib
