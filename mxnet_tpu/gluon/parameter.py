"""gluon.Parameter / ParameterDict.

Parity: `python/mxnet/gluon/parameter.py` (Parameter with deferred
allocation, grad_req, per-context replicas; ParameterDict with prefix
namespacing, save/load :854,879).

TPU-native notes: per-context replicas exist for API parity with the
reference's multi-GPU data parallelism; the TPU-first scaling path keeps ONE
logical parameter and shards it over a `jax.sharding.Mesh` (see
`mxnet_tpu.parallel`). `Parameter.shard_spec` carries the GSPMD annotation —
the redesign of the reference's `group2ctx` model parallelism
(`graph_executor.cc:920 AssignContext`).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import initializer

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization (parity parameter.py:40)."""


def _shape_complete(shape):
    return shape is not None and all(int(s) > 0 for s in shape)


class Parameter:
    """A Container holding parameters (weights) of Blocks.

    Parity: `gluon/parameter.py class Parameter`. ``grad_req`` in
    {'write', 'add', 'null'}; shape entries of 0 mean unknown (deferred
    init resolved on first forward).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default",
                 shard_spec=None):
        self._var = None
        self._data = None           # dict: dev-key -> NDArray
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self.shard_spec = shard_spec
        self.grad_req = grad_req
        self.attributes = {}
        self._trainer = None

    def _set_trainer(self, trainer):
        if self._trainer is not None and trainer is not None and \
                self._trainer is not trainer and self._stype != "default":
            raise RuntimeError(
                f"Failed to set the trainer for Parameter '{self.name}' because it was "
                f"already set. More than one trainers for a sparse Parameter is not "
                f"supported.")
        self._trainer = trainer

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={_np.dtype(self.dtype).name})"

    # -- properties ---------------------------------------------------------

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null"), f"grad_req must be one of write/add/null, got {req}"
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for arr in self._data.values():
                    arr.grad = None
                    arr.grad_req = "null"
        elif self._data is not None and self._grad is None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(int(s) for s in new_shape) if new_shape is not None else None
            return
        assert len(self._shape) == len(new_shape) and all(
            j in (0, i) for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {new_shape} is incompatible with given shape {self._shape}"
        self._shape = tuple(int(s) for s in new_shape)

    @property
    def stype(self):
        return self._stype

    # -- init ---------------------------------------------------------------

    def initialize(self, init=None, ctx=None, default_init=initializer.Uniform(),
                   force_reinit=False):
        """Initialize parameter/gradient arrays (parity parameter.py:360)."""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None and self.init is not None:
            init = self.init
        # DELIBERATE DIVERGENCE from the reference: init stays None when
        # the param merely inherits the GLOBAL default_init —
        # _finish_deferred_init then routes through the name-suffix
        # dispatch (weight->init_weight, bias->zeros, ...). The reference
        # instead resolves default_init into the InitDesc `__init__` attr,
        # so a raw non-suffix name ('transitions') silently takes the
        # global initializer there; here it raises 'Unknown initialization
        # pattern'. The stricter behavior is intentional — an unmatched
        # name fails loudly instead of training with a surprise init — and
        # collapsing default_init into init here would also force e.g.
        # Xavier onto a 1-d "bias" param. Pinned (as a divergence) by
        # test_custom_named_parameter_init_dispatch.
        if not _shape_complete(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(f"Cannot initialize Parameter '{self.name}' because it has "
                             f"invalid shape: {self._shape}.")
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        assert _shape_complete(self._shape), \
            f"Cannot initialize Parameter '{self.name}' because it has " \
            f"invalid shape: {self._shape}. Please specify in_units, " \
            f"in_channels, etc for `Block`s."
        from .. import autograd
        with autograd.pause():
            if data is None:
                data = nd.zeros(self._shape, dtype=self.dtype, ctx=cpu())
                # `init` was resolved in initialize(): explicit arg > param.init
                # > default_init (reference parameter.py _finish_deferred_init).
                # A param-specific init rides the InitDesc `__init__` attr so
                # it applies REGARDLESS of the name suffix (the reference's
                # mechanism — a custom-named param like a CRF transition
                # matrix must not hit the weight/bias pattern fallback).
                attrs = {}
                # attrs ride the RESOLVED initializer (explicit arg >
                # param.init — resolved in initialize()), never self.init
                # directly, or an explicit initialize(init=...) would lose
                # to the stored one
                if init is not None:
                    init_obj = initializer.create(init)
                    # the attr route is a dumps/loads round trip, so only
                    # REGISTERED initializer classes can ride it; ad-hoc
                    # ones (Constant's closure Init) already bypass the
                    # suffix dispatch themselves
                    if type(init_obj).__name__.lower() in \
                            initializer._INIT_REGISTRY:
                        attrs["__init__"] = init_obj.dumps()
                initializer.create(init if init is not None else default_init)(
                    initializer.InitDesc(self.name, attrs), data)
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        if isinstance(ctx_list, Context):
            ctx_list = [ctx_list]
        self._ctx_list = list(ctx_list)
        self._data = {}
        for c in self._ctx_list:
            self._data[self._dev_key(c)] = data.copyto(c)
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        from .. import autograd
        self._grad = {}
        for k, arr in self._data.items():
            if self._grad_stype == "row_sparse":
                from ..ndarray.sparse import RowSparseNDArray
                import jax.numpy as jnp

                g = RowSparseNDArray(
                    nd.NDArray(jnp.zeros((0,) + tuple(arr.shape[1:]), arr.dtype)),
                    nd.NDArray(jnp.zeros((0,), jnp.int32)),
                    tuple(arr.shape), arr.context)
            else:
                g = nd.zeros(arr.shape, dtype=arr.dtype, ctx=arr.context)
            self._grad[k] = g
            autograd.mark_variables(arr, g, self._grad_req)

    @staticmethod
    def _dev_key(ctx):
        return (ctx.device_type, ctx.device_id)

    def _check_and_get(self, arr_dict, ctx):
        if arr_dict is not None:
            if ctx is list:
                return list(arr_dict.values())
            if ctx is None:
                if len(arr_dict) == 1:
                    return next(iter(arr_dict.values()))
                ctx = current_context()
            if isinstance(ctx, list):
                return [self._check_and_get(arr_dict, c) for c in ctx]
            key = self._dev_key(ctx)
            if key in arr_dict:
                return arr_dict[key]
            raise RuntimeError(f"Parameter '{self.name}' was not initialized on context {ctx}. "
                               f"It was only initialized on {self._ctx_list}.")
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet because "
                f"initialization was deferred. Actual initialization happens during "
                f"the first forward pass. Please pass one batch of data through "
                f"the network before accessing Parameters.")
        raise RuntimeError(
            f"Parameter '{self.name}' has not been initialized. Note that you should "
            f"initialize parameters and create Trainer with Block.collect_params() "
            f"instead of Block.params because the later does not include Parameters "
            f"of nested child Blocks")

    # -- accessors ----------------------------------------------------------

    def data(self, ctx=None):
        return self._check_and_get(self._data, ctx)

    def list_data(self):
        self._check_and_get(self._data, list)
        return [self._data[self._dev_key(c)] for c in self._ctx_list]

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(f"Cannot get gradient array for Parameter '{self.name}' "
                               f"because grad_req='null'")
        return self._check_and_get(self._grad, ctx)

    def list_grad(self):
        if self._data is not None and self._grad is None:
            raise RuntimeError(f"Cannot get gradient array for Parameter '{self.name}' "
                               f"because grad_req='null'")
        self._check_and_get(self._grad, list)
        return [self._grad[self._dev_key(c)] for c in self._ctx_list]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError(f"Parameter '{self.name}' has not been initialized")
        return self._ctx_list

    def set_data(self, data):
        """Set this parameter's value on all contexts."""
        self.shape = data.shape
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter '{self.name}' has not been initialized"
            init, ctx, default_init, _ = self._deferred_init
            self._deferred_init = (init, ctx, default_init,
                                   data if isinstance(data, NDArray) else nd.array(data))
            return
        from .. import autograd
        with autograd.pause():
            for k, arr in self._data.items():
                src = data if isinstance(data, NDArray) else nd.array(data)
                arr._data = src.copyto(arr.context)._data

    def zero_grad(self):
        if self._grad is None:
            return
        from .. import autograd
        with autograd.pause():
            for g in self._grad.values():
                g[:] = 0

    def reset_ctx(self, ctx):
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = next(iter(self._data.values()))
            self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(f"Cannot reset context for Parameter '{self.name}' because it "
                             "has not been initialized.")

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        from .. import autograd
        with autograd.pause():
            for k in list(self._data):
                self._data[k] = self._data[k].astype(dtype)
            if self._grad is not None:
                for k in list(self._grad):
                    self._grad[k] = self._grad[k].astype(dtype)
                    autograd.mark_variables(self._data[k], self._grad[k], self._grad_req)

    def var(self):
        """The Symbol representing this parameter (symbolic API)."""
        if self._var is None:
            from .. import symbol
            self._var = symbol.var(self.name, shape=self.shape, dtype=self.dtype,
                                   lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                                   init=self.init)
        return self._var

    def row_sparse_data(self, row_id):
        """Rows of this parameter selected by ``row_id`` as a
        RowSparseNDArray (parity `gluon/parameter.py row_sparse_data`).

        The reference requires `stype='row_sparse'` and pulls the rows from
        the trainer's kvstore (dist servers hold the authority copy). The
        TPU design stores the weight dense in HBM (gathers are XLA-native);
        when a dist trainer is attached the rows are refreshed through
        `kvstore.row_sparse_pull` first, then gathered — only O(rows)
        touches the host/wire, never the full table."""
        from ..base import MXNetError
        from ..ndarray.sparse import RowSparseNDArray
        import jax.numpy as jnp

        if self._stype != "row_sparse" and self._grad_stype != "row_sparse":
            raise MXNetError(
                f"Parameter '{self.name}' is not sparse (stype={self._stype}, "
                f"grad_stype={self._grad_stype}); use data() instead")
        if not isinstance(row_id, NDArray):
            row_id = nd.array(row_id, dtype="int64")
        trainer = getattr(self, "_trainer", None)
        if trainer is not None and getattr(trainer, "_kvstore", None) is not None \
                and "dist" in trainer._kvstore.type:
            trainer._row_sparse_pull(self, row_id)
        arr = self._check_and_get(self._data, None)
        return self._gather_rows(arr, row_id)

    @staticmethod
    def _gather_rows(arr, row_id):
        from ..ndarray.sparse import RowSparseNDArray
        import jax.numpy as jnp

        uniq = jnp.unique(row_id._data.reshape(-1).astype(jnp.int32)) \
            if row_id.size else jnp.zeros((0,), jnp.int32)
        rows = jnp.take(arr._data, uniq, axis=0) if uniq.size else \
            jnp.zeros((0,) + tuple(arr.shape[1:]), arr.dtype)
        return RowSparseNDArray(NDArray(rows), NDArray(uniq), tuple(arr.shape),
                                arr.context)

    def list_row_sparse_data(self, row_id):
        """One RowSparseNDArray per context, aligned with list_ctx()
        (parity gluon/parameter.py list_row_sparse_data)."""
        trainer = getattr(self, "_trainer", None)
        if trainer is not None and getattr(trainer, "_kvstore", None) is not None \
                and "dist" in trainer._kvstore.type:
            trainer._row_sparse_pull(self, row_id)
        arrs = self._check_and_get(self._data, list)
        return [self._gather_rows(a, row_id) for a in arrs]


class Constant(Parameter):
    """A constant parameter (never updated by gradients).

    Parity: `gluon/parameter.py class Constant`.
    """

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(self2, _, arr):
                arr[:] = value.asnumpy()

            # constants may have any name; bypass suffix dispatch entirely
            _init_default = _init_weight
            _init_bias = _init_weight
            _init_gamma = _init_weight
            _init_beta = _init_weight

        # instance passed directly (initializer.create accepts instances) —
        # no global-registry mutation, so same-named constants can't collide
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=Init(), differentiable=False)


class ParameterDict:
    """A dictionary managing a set of Parameters (parity gluon/parameter.py)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}  # OrderedDict semantics (py3.7 dicts ordered)
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __repr__(self):
        name = self._prefix + " " if self._prefix else ""
        return f"{name}(\n" + "\n".join(f"  {v}" for v in self.values()) + "\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._shared._params[name]
        return None

    def get(self, name, **kwargs):
        """Retrieve or create a Parameter ``self.prefix + name``."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        param.shape = v
                        continue
                    assert v is None or v == existing or (k == "dtype" and
                            _np.dtype(v) == _np.dtype(existing)), \
                        f"Cannot retrieve Parameter '{name}' because desired attribute " \
                        f"does not match with stored for attribute '{k}': " \
                        f"desired '{v}' vs stored '{getattr(param, k)}'"
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'. Please specify value "
                               "if you want to create a new constant.")
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None:
            assert isinstance(param, Constant), \
                f"Parameter '{name}' already exists but it is not a constant."
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    f"Cannot update self with other because they have different " \
                    f"Parameters with the same name '{k}'"
            else:
                self._params[k] = v

    def initialize(self, init=initializer.Uniform(), ctx=None, verbose=False,
                   force_reinit=False):
        if verbose:
            init.set_verbosity(verbose=verbose)
        for v in self.values():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        """Save parameters to an .params file (reference NDArray dict format,
        `ndarray.cc:1578` / `c_api.cc MXNDArraySave`)."""
        arg_dict = {}
        for param in self.values():
            weight = param._reduce() if hasattr(param, "_reduce") else param.data(
                param.list_ctx()[0]).copyto(cpu())
            if not param.name.startswith(strip_prefix):
                raise ValueError(f"Prefix '{strip_prefix}' is to be stripped before saving, "
                                 f"but Parameter's name '{param.name}' does not start "
                                 f"with '{strip_prefix}'")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        if restore_prefix:
            for name in self.keys():
                assert name.startswith(restore_prefix), \
                    f"restore_prefix is '{restore_prefix}' but Parameter name '{name}' " \
                    f"does not start with it"
        lprefix = len(restore_prefix)
        loaded = nd.load(filename)
        arg_dict = {(restore_prefix + k[4:] if k.startswith("arg:") or k.startswith("aux:")
                     else restore_prefix + k): v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter '{name[lprefix:]}' is missing in file '{filename}'"
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    f"Parameter '{name[lprefix:]}' loaded from file '{filename}' is not " \
                    f"present in ParameterDict"
                continue
            self[name]._load_init(arg_dict[name])

    def list_ctx(self):
        s = set()
        for v in self.values():
            s.update(v.list_ctx())
        return sorted(s, key=str)


def _load_init(self, data, ctx=None):
    """Initialize a Parameter directly from a loaded array."""
    if self.shape is not None and any(self.shape):
        for self_dim, data_dim in zip(self.shape, data.shape):
            assert self_dim in (0, data_dim), \
                f"Failed loading Parameter '{self.name}' from saved params: " \
                f"shape incompatible expected {self.shape} vs saved {data.shape}"
        self.shape = tuple(i if i != 0 else j for i, j in zip(self.shape, data.shape))
    if self.dtype is not None:
        data = data.astype(self.dtype, copy=False)
    if self._data is None:
        if self._deferred_init:
            ctx = self._deferred_init[1]
        elif ctx is None:
            ctx = [cpu()]
        self._init_impl(data, ctx)
    else:
        self.set_data(data)
    self._deferred_init = ()


Parameter._load_init = _load_init
