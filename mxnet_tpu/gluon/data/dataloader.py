"""gluon.data.DataLoader.

Parity: `python/mxnet/gluon/data/dataloader.py` — batching, samplers,
`batchify_fn`, multi-worker loading.

TPU-native redesign of the worker path: the reference forks processes and
ships NDArrays through POSIX shared memory (`cpu_shared_storage_manager.h`,
`dataloader.py:55-120`) because its arrays live in worker-process heaps.
Here workers run in a thread pool by default: batch assembly is
numpy-bound (releases the GIL) and the device transfer happens once per
batch on the main thread via a single `jax.device_put` — the host→HBM DMA
queue replaces the reference's shm+pickle relay. `num_workers>0` uses a
`multiprocessing.Pool` with numpy (picklable) batches when
`thread_pool=False`.
"""
from __future__ import annotations

import multiprocessing
import multiprocessing.pool
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack items into a batch (parity dataloader.py:127)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


def _as_numpy_batchify(data):
    """Worker-process batchify: keep numpy (picklable, no device handles)."""
    if isinstance(data[0], tuple):
        return [_as_numpy_batchify(i) for i in zip(*data)]
    return np.asarray(data)


class _WorkerFn:
    """Top-level callable (picklable) fetching+batchifying one index batch."""

    def __init__(self, dataset, batchify_fn):
        self._dataset = dataset
        self._batchify_fn = batchify_fn

    def __call__(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])


def _to_nd(batch, pin_memory=False):
    if isinstance(batch, (list, tuple)):
        return [_to_nd(b) for b in batch]
    if isinstance(batch, NDArray):
        return batch
    return nd.array(batch)


class DataLoader:
    """Loads data from a Dataset, returns mini-batches (parity
    dataloader.py:422)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=True):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")

        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    yield _to_nd(self._batchify_fn(
                        [self._dataset[idx] for idx in batch]), self._pin_memory)
            return same_process_iter()
        return _MultiWorkerIter(self)

    def __len__(self):
        return len(self._batch_sampler)


class _MultiWorkerIter:
    """Prefetching iterator over worker pool results (parity
    dataloader.py:326 _MultiWorkerIter)."""

    def __init__(self, loader):
        self._loader = loader
        bf = loader._batchify_fn
        if loader._thread_pool:
            self._pool = ThreadPoolExecutor(max_workers=loader._num_workers)
            self._fn = _WorkerFn(loader._dataset, bf)
        else:
            self._pool = multiprocessing.Pool(loader._num_workers)
            self._fn = _WorkerFn(
                loader._dataset,
                _as_numpy_batchify if bf is default_batchify_fn else bf)
        self._batch_iter = iter(loader._batch_sampler)
        self._pending = []
        self._exhausted = False
        for _ in range(max(1, loader._prefetch)):
            self._push_next()

    def _push_next(self):
        indices = next(self._batch_iter, None)
        if indices is None:
            self._exhausted = True
            return
        if isinstance(self._pool, ThreadPoolExecutor):
            self._pending.append(self._pool.submit(self._fn, indices))
        else:
            self._pending.append(self._pool.apply_async(self._fn, (indices,)))

    def __next__(self):
        if not self._pending:
            self._shutdown()
            raise StopIteration
        fut = self._pending.pop(0)
        self._push_next()
        batch = fut.result() if hasattr(fut, "result") else fut.get()
        return _to_nd(batch, self._loader._pin_memory)

    def __iter__(self):
        return self

    def _shutdown(self):
        if isinstance(self._pool, ThreadPoolExecutor):
            self._pool.shutdown(wait=False)
        else:
            self._pool.terminate()
