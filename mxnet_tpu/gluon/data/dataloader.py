"""gluon.data.DataLoader.

Parity: `python/mxnet/gluon/data/dataloader.py` — batching, samplers,
`batchify_fn`, multi-worker loading.

TPU-native redesign of the worker path: the reference forks processes and
ships NDArrays through POSIX shared memory (`cpu_shared_storage_manager.h`,
`dataloader.py:55-120`) because its arrays live in worker-process heaps.
Here workers run in a thread pool by default: batch assembly is
numpy-bound (releases the GIL) and the device transfer happens once per
batch on the main thread via a single `jax.device_put` — the host→HBM DMA
queue replaces the reference's shm+pickle relay.

`num_workers>0, thread_pool=False` uses a `multiprocessing.Pool`; when the
native runtime is built, worker→parent batches travel through the
`SharedMemoryArena` (`src/arena.cc`, the CPUSharedStorageManager role):
the worker writes the assembled numpy batch into a named POSIX shm
segment and returns only metadata; the parent maps the segment zero-copy
and feeds `jax.device_put` straight from it — no multi-MB pickle through
the pool pipe. Pickle remains the fallback when the .so is absent or shm
creation fails.
"""
from __future__ import annotations

import multiprocessing
import multiprocessing.pool
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack items into a batch (parity dataloader.py:127)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


def _as_numpy_batchify(data):
    """Worker-process batchify: keep numpy (picklable, no device handles)."""
    if isinstance(data[0], tuple):
        return [_as_numpy_batchify(i) for i in zip(*data)]
    return np.asarray(data)


class _WorkerFn:
    """Top-level callable (picklable) fetching+batchifying one index batch."""

    def __init__(self, dataset, batchify_fn):
        self._dataset = dataset
        self._batchify_fn = batchify_fn

    def __call__(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])


def _flatten_batch(batch):
    """Flatten a (possibly nested list) numpy batch into (leaves, treespec);
    treespec is 'a' for an array or a list of specs."""
    if isinstance(batch, (list, tuple)):
        leaves, spec = [], []
        for b in batch:
            sub_leaves, sub_spec = _flatten_batch(b)
            leaves.extend(sub_leaves)
            spec.append(sub_spec)
        return leaves, spec
    return [np.ascontiguousarray(batch)], "a"


def _unflatten_batch(leaves, spec, cursor=None):
    cursor = cursor if cursor is not None else [0]
    if spec == "a":
        out = leaves[cursor[0]]
        cursor[0] += 1
        return out
    return [_unflatten_batch(leaves, s, cursor) for s in spec]


class _ShmWorkerFn:
    """Worker fn shipping batches through the SharedMemoryArena
    (`src/arena.cc`; reference `cpu_shared_storage_manager.h` +
    `dataloader.py:55` rebuild_ndarray): writes the assembled batch into a
    named shm segment, returns (segment_name, per-leaf metadata, treespec)
    — a few hundred bytes through the pool pipe instead of the batch."""

    def __init__(self, dataset, batchify_fn, tag):
        self._dataset = dataset
        self._batchify_fn = batchify_fn
        self._tag = tag

    def __call__(self, job):
        slot, indices = job
        batch = self._batchify_fn([self._dataset[i] for i in indices])
        leaves, spec = _flatten_batch(batch)
        metas, total = [], 0
        for leaf in leaves:
            off = total
            total += leaf.nbytes
            metas.append((leaf.shape, leaf.dtype.str, off))
        from ... import lib

        name = f"/mxtpu_dl_{self._tag}_{os.getpid()}_{slot}"
        try:
            seg = lib.shared_memory(name, size=max(total, 1), create=True)
        except OSError:
            seg = None  # e.g. /dev/shm full (arena.cc reserves pages up
            #             front, so exhaustion fails here, not as SIGBUS)
        if seg is None:  # .so missing or shm unavailable: pickle fallback
            return ("pickle", leaves, spec)
        mv = memoryview(seg.asarray())  # uint8 view over the segment
        for leaf, (_, _, off) in zip(leaves, metas):
            dst = np.ndarray(leaf.shape, leaf.dtype, buffer=mv, offset=off)
            np.copyto(dst, leaf)  # ONE memcpy into the mapped segment
        seg.detach()
        return ("shm", name, metas, spec)


def _read_shm_batch(msg):
    """Parent side: map the worker's segment, copy out per-leaf arrays
    (the device_put is the real consumer), then unlink the segment."""
    from ... import lib

    if msg[0] == "pickle":
        _, leaves, spec = msg
        return _unflatten_batch(leaves, spec)
    _, name, metas, spec = msg

    from ...resilience import inject, retry_call

    def _attach():
        inject("shm", name)
        seg = lib.shared_memory(name, create=False)
        if seg is None:
            raise OSError(f"DataLoader: cannot attach shm segment {name}")
        return seg

    # attach is idempotent; a transient attach failure (worker still
    # publishing, /dev/shm pressure) gets the resilience retry budget
    seg = retry_call(_attach, desc=f"shm attach {name}")
    try:
        mv = memoryview(seg.asarray())
        leaves = []
        for shape, dtype, off in metas:
            src = np.ndarray(shape, np.dtype(dtype), buffer=mv, offset=off)
            leaves.append(src.copy())  # ONE memcpy out of the segment
    finally:
        seg.unlink()
        seg.detach()
    return _unflatten_batch(leaves, spec)


def _to_nd(batch, pin_memory=False):
    if isinstance(batch, (list, tuple)):
        return [_to_nd(b) for b in batch]
    if isinstance(batch, NDArray):
        return batch
    return nd.array(batch)


class DataLoader:
    """Loads data from a Dataset, returns mini-batches (parity
    dataloader.py:422)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=True):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")

        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    yield _to_nd(self._batchify_fn(
                        [self._dataset[idx] for idx in batch]), self._pin_memory)
            return same_process_iter()
        return _MultiWorkerIter(self)

    def __len__(self):
        return len(self._batch_sampler)


class _MultiWorkerIter:
    """Prefetching iterator over worker pool results (parity
    dataloader.py:326 _MultiWorkerIter)."""

    def __init__(self, loader):
        self._loader = loader
        self._shm = False
        self._slot = 0
        bf = loader._batchify_fn
        if loader._thread_pool:
            self._pool = ThreadPoolExecutor(max_workers=loader._num_workers)
            self._fn = _WorkerFn(loader._dataset, bf)
        else:
            from ... import lib

            self._pool = multiprocessing.Pool(loader._num_workers)
            np_bf = _as_numpy_batchify if bf is default_batchify_fn else bf
            if lib.native_available():
                # batches ride the SharedMemoryArena, not the pool pipe
                self._shm = True
                self._fn = _ShmWorkerFn(loader._dataset, np_bf, id(self))
            else:
                self._fn = _WorkerFn(loader._dataset, np_bf)
        self._batch_iter = iter(loader._batch_sampler)
        self._pending = []
        self._exhausted = False
        for _ in range(max(1, loader._prefetch)):
            self._push_next()

    def _push_next(self):
        indices = next(self._batch_iter, None)
        if indices is None:
            self._exhausted = True
            return
        if isinstance(self._pool, ThreadPoolExecutor):
            self._pending.append(self._pool.submit(self._fn, indices))
        elif self._shm:
            self._slot += 1
            self._pending.append(
                self._pool.apply_async(self._fn, ((self._slot, indices),)))
        else:
            self._pending.append(self._pool.apply_async(self._fn, (indices,)))

    def __next__(self):
        if not self._pending:
            self._shutdown()
            raise StopIteration
        fut = self._pending.pop(0)
        self._push_next()
        batch = fut.result() if hasattr(fut, "result") else fut.get()
        if self._shm:
            batch = _read_shm_batch(batch)
        return _to_nd(batch, self._loader._pin_memory)

    def __iter__(self):
        return self

    def _shutdown(self):
        if self._shm and self._pending:
            # drain in-flight batches and unlink their segments — an
            # abandoned epoch must not leak named /dev/shm files
            from ... import lib

            for fut in self._pending:
                try:
                    msg = fut.get(timeout=10)
                except Exception:  # noqa: BLE001 — worker already gone
                    continue
                if isinstance(msg, tuple) and msg and msg[0] == "shm":
                    lib.shm_unlink(msg[1])
            self._pending = []
        if isinstance(self._pool, ThreadPoolExecutor):
            self._pool.shutdown(wait=False)
        else:
            self._pool.terminate()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:  # noqa: BLE001
            pass
