"""Vision datasets (parity: `python/mxnet/gluon/data/vision/datasets.py`).

MNIST/FashionMNIST (idx format), CIFAR10/100 (binary format),
ImageRecordDataset (.rec), ImageFolderDataset. This environment has no
network egress, so `root` must already contain the raw files (the
reference's auto-download is replaced by a clear error listing what to
place where).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from .... import ndarray as nd
from ....base import MXNetError
from .. import dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(dataset.Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        if not os.path.isdir(self._root):
            os.makedirs(self._root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)


class MNIST(_DownloadedDataset):
    """MNIST (reference datasets.py MNIST). Expects the idx files
    (train-images-idx3-ubyte[.gz] etc.) under `root`."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _find(self, base):
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise MXNetError(
            f"{base}[.gz] not found under {self._root}; this environment has "
            f"no network egress — place the raw idx files there")

    def _get_data(self):
        imgs, labels = (self._train_files if self._train else self._test_files)
        data = _read_idx_images(self._find(imgs))
        label = _read_idx_images(self._find(labels))
        self._data = nd.array(data[..., None].astype("uint8"), dtype="uint8")
        self._label = label.astype("int32")


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python pickle batches under `root`
    (cifar-10-batches-py/ or the .tar.gz)."""

    _batch_dir = "cifar-10-batches-py"

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _load_batches(self, names):
        d = os.path.join(self._root, self._batch_dir)
        if not os.path.isdir(d):
            tar = os.path.join(self._root, "cifar-10-python.tar.gz")
            if os.path.exists(tar):
                with tarfile.open(tar) as t:
                    t.extractall(self._root)
            else:
                raise MXNetError(
                    f"{self._batch_dir}/ not found under {self._root}; place "
                    f"the CIFAR-10 python batches there (no network egress)")
        data, labels = [], []
        for n in names:
            with open(os.path.join(d, n), "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            data.append(batch["data"])
            labels.extend(batch.get("labels", batch.get("fine_labels")))
        data = _np.concatenate(data).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return data.astype("uint8"), _np.asarray(labels, dtype="int32")

    def _get_data(self):
        names = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        data, label = self._load_batches(names)
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class CIFAR100(CIFAR10):
    _batch_dir = "cifar-100-python"

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=True, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root=root, train=train, transform=transform)

    def _get_data(self):
        names = ["train"] if self._train else ["test"]
        data, label = self._load_batches(names)
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class ImageRecordDataset(dataset.RecordFileDataset):
    """Dataset over a .rec of packed images (reference ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....image import imdecode
        from .... import recordio

        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        image = imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(image, label)
        return image, label


class ImageFolderDataset(dataset.Dataset):
    """root/class_x/xxx.jpg folder layout (reference ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".bmp"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ....image import imread

        img = imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
