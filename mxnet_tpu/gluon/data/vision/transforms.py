"""Vision transforms (parity: `python/mxnet/gluon/data/vision/transforms.py`).

Blocks so they compose with nn.Sequential and hybridize; math runs on
HWC uint8/float inputs the datasets produce, emitting CHW float for
ToTensor — the reference's conventions exactly.
"""
from __future__ import annotations

import random as _random

import numpy as _np

from .... import ndarray as nd
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from ....image import image as _img

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting", "RandomGray", "CropResize"]


class Compose(Sequential):
    """Sequentially compose transforms (reference transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype) if hasattr(x, "astype") else \
            F.cast(x, dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference ToTensor)."""

    def forward(self, x):
        arr = x.asnumpy().astype("float32") / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return nd.array(arr)


class Normalize(Block):
    """(x - mean) / std on CHW float (reference Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, "float32")
        self._std = _np.asarray(std, "float32")

    def forward(self, x):
        arr = x.asnumpy()
        c = arr.shape[-3]
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd.array((arr - mean) / std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        if isinstance(self._size, int):
            if self._keep:
                return _img.resize_short(x, self._size, self._interpolation)
            return _img.imresize(x, self._size, self._size,
                                 self._interpolation)
        return _img.imresize(x, self._size[0], self._size[1],
                             self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interpolation = interpolation

    def forward(self, x):
        return _img.center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        return _img.random_size_crop(x, self._size, self._scale, self._ratio,
                                     self._interpolation)[0]


class CropResize(Block):
    def __init__(self, x0, y0, width, height, size=None, interpolation=1):
        super().__init__()
        self._args = (x0, y0, width, height)
        self._size = size
        self._interpolation = interpolation

    def forward(self, x):
        out = _img.fixed_crop(x, *self._args)
        if self._size:
            out = _img.imresize(out, self._size[0], self._size[1],
                                self._interpolation)
        return out


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _random.random() < self._p:
            return nd.array(x.asnumpy()[:, ::-1].copy(), dtype=str(x.dtype))
        return x


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _random.random() < self._p:
            return nd.array(x.asnumpy()[::-1].copy(), dtype=str(x.dtype))
        return x


class _JitterBlock(Block):
    _aug_cls = None

    def __init__(self, amount):
        super().__init__()
        self._aug = self._aug_cls(amount)

    def forward(self, x):
        return self._aug(x)


class RandomBrightness(_JitterBlock):
    _aug_cls = _img.BrightnessJitterAug


class RandomContrast(_JitterBlock):
    _aug_cls = _img.ContrastJitterAug


class RandomSaturation(_JitterBlock):
    _aug_cls = _img.SaturationJitterAug


class RandomHue(_JitterBlock):
    _aug_cls = _img.HueJitterAug


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._aug = _img.ColorJitterAug(brightness, contrast, saturation)
        self._hue = _img.HueJitterAug(hue) if hue else None

    def forward(self, x):
        x = self._aug(x)
        if self._hue:
            x = self._hue(x)
        return x


class RandomLighting(Block):
    def __init__(self, alpha):
        super().__init__()
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        self._aug = _img.LightingAug(alpha, eigval, eigvec)

    def forward(self, x):
        return self._aug(x)


class RandomGray(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._aug = _img.RandomGrayAug(p)

    def forward(self, x):
        return self._aug(x)
