"""gluon.data (parity `python/mxnet/gluon/data/__init__.py`)."""
from .dataset import *
from .sampler import *
from .dataloader import *

from . import dataset
from . import sampler
from . import dataloader

try:
    from . import vision
except ImportError:  # pragma: no cover - during staged build only
    vision = None
