"""Pretrained-weight store (parity `python/mxnet/gluon/model_zoo/model_store.py`).

The reference downloads `.params` files from an S3 repo. This environment
has no network egress, so `get_model_file` only resolves files already
present under `root` (drop pretrained checkpoints there manually); a
missing file raises with instructions rather than attempting a download.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]

_paths_checked = ("{root}/{name}.params",)


def get_model_file(name, root="~/.mxnet/models"):
    """Return the path of a locally stored pretrained model file."""
    root = os.path.expanduser(root)
    for fmt in _paths_checked:
        path = fmt.format(root=root, name=name)
        if os.path.exists(path):
            return path
    raise FileNotFoundError(
        f"Pretrained weights for '{name}' not found under {root}. "
        "This environment has no network access; place the parameter file "
        f"at {root}/{name}.params to use pretrained=True.")


def purge(root="~/.mxnet/models"):
    """Remove all cached model files."""
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
