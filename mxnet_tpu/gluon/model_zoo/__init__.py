"""gluon.model_zoo (parity `python/mxnet/gluon/model_zoo/__init__.py`).

Populated by `vision` (resnet/vgg/densenet/... — SURVEY.md §2.3) as the
model families land.
"""
try:
    from . import vision  # noqa: F401
except ImportError:  # pragma: no cover - during staged build only
    pass
