"""gluon utilities (parity: `python/mxnet/gluon/utils.py`)."""
from __future__ import annotations

import os

import numpy as _np

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1", "download",
           "shape_is_known"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into `num_slice` slices along `batch_axis`
    (parity gluon/utils.py:31 — the Module-era batch slicer,
    `executor_group.py:65`)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into {num_slice} slices "
            f"along axis {batch_axis}. Use a batch size that's multiple of {num_slice} "
            f"or set even_split=False to allow uneven partitioning of data.")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    if even_split:
        slices = [nd.slice_axis(data, axis=batch_axis, begin=i * step, end=(i + 1) * step)
                  for i in range(num_slice)]
    else:
        slices = [nd.slice_axis(data, axis=batch_axis, begin=i * step,
                                end=(i + 1) * step if i < num_slice - 1 else size)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data along batch_axis and load each slice onto one context
    (parity gluon/utils.py:81)."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the sum of their 2-norm is smaller than max_norm
    (parity gluon/utils.py:115)."""
    assert len(arrays) > 0
    ctx = arrays[0].context
    total_norm = nd.add_n(*[(arr.reshape((-1,)) ** 2).sum().as_in_context(ctx)
                            for arr in arrays])
    total_norm = nd.sqrt(total_norm)
    scale = max_norm / (total_norm.asscalar() + 1e-8)
    if check_isfinite and not _np.isfinite(total_norm.asscalar()):
        import warnings
        warnings.warn(UserWarning("nan or inf is detected. Clipping results will be "
                                  "undefined."), stacklevel=2)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm.asscalar()


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5, verify_ssl=True):
    """Download a file (parity gluon/utils.py:188). This build runs with zero
    network egress: if the file is already on disk it is used, otherwise a
    clear error tells the user to provide it locally."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise RuntimeError(
        f"download('{url}') requires network access, which is unavailable in this "
        f"environment. Place the file at '{fname}' manually.")


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)


def _brief_print_list(lst, limit=7):
    lst = list(lst)
    if len(lst) > limit:
        return ", ".join(map(repr, lst[:limit // 2])) + ", ..., " + \
            ", ".join(map(repr, lst[-limit // 2:]))
    return ", ".join(map(repr, lst))
