"""gluon.contrib.nn — experimental layer containers.

Parity: `python/mxnet/gluon/contrib/nn/basic_layers.py` (Concurrent,
HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle1D/2D/3D).
"""
from .basic_layers import *
from . import basic_layers
