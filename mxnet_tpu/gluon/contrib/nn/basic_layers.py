"""Contrib layer containers (parity `python/mxnet/gluon/contrib/nn/basic_layers.py`).

TPU note: `Concurrent` branches are independent subgraphs; under hybridize
XLA schedules them in one program, so there is no host-side fork/join to
manage (the reference relied on the dependency engine for overlap).
"""
from __future__ import annotations

from ... import nn
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential, BatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input and concat their outputs along `axis`."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        from .... import ndarray as F
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (parity contrib/nn/basic_layers.py:80)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping — placeholder branch in Concurrent blocks."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose gradient is row_sparse (parity contrib
    basic_layers.py:118). Backward emits a `RowSparseNDArray` of only the
    touched rows (`ops/indexing.py _embedding_sparse_vjp`); the optimizer's
    sparse branch then updates those rows in place — a lookup into a 1M-row
    table costs O(batch) in backward+update, never O(table)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": True}
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      init=weight_initializer, dtype=dtype,
                                      grad_stype="row_sparse")

    def forward(self, x):
        from .... import ndarray as F
        return F.Embedding(x, self.weight.data(x.context), **self._kwargs)

    def __repr__(self):
        s = "{block_name}({input_dim} -> {output_dim}, {dtype})"
        return s.format(block_name=self.__class__.__name__, **self._kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (parity contrib
    basic_layers.py:152 wrapping `_contrib_SyncBatchNorm`,
    `src/operator/contrib/sync_batch_norm.cc`).

    TPU-native: under pjit/shard_map the batch axis is sharded over the
    mesh; batch statistics are made global with a `psum` inside the op
    (see `ops/nn.py:_sync_batch_norm`) instead of the reference's
    cross-GPU key-value barrier.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.contrib.SyncBatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._kwargs["eps"], momentum=self._kwargs["momentum"],
            fix_gamma=self._kwargs["fix_gamma"],
            use_global_stats=self._kwargs["use_global_stats"],
            name="fwd")


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = tuple(int(f) for f in factor)
        except TypeError:
            self._factors = (int(factor),) * ndim
        assert len(self._factors) == ndim, \
            f"wrong factor length {self._factors} for {ndim}d pixel shuffle"

    def __repr__(self):
        return f"{self.__class__.__name__}({self._factors})"


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) → (N, C, W*f) sub-pixel upsample."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        f, = self._factors
        x = F.reshape(x, (0, -4, -1, f, 0))      # (N, C, f, W)
        x = F.transpose(x, (0, 1, 3, 2))          # (N, C, W, f)
        x = F.reshape(x, (0, 0, -3))              # (N, C, W*f)
        return x


class PixelShuffle2D(_PixelShuffle):
    """(N, C*f1*f2, H, W) → (N, C, H*f1, W*f2)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        x = F.reshape(x, (0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, (0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, (0, 1, 4, 2, 5, 3))
        x = F.reshape(x, (0, 0, -3, -3))
        return x


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) → (N, C, D*f1, H*f2, W*f3)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        # Peel one factor at a time so every intermediate stays <= 6-D and
        # only the supported reshape codes (0/-1/-3/-4) are needed.
        f1, f2, f3 = self._factors
        x = F.reshape(x, (0, -4, -1, f3, 0, 0, 0))    # (N, C*f1*f2, f3, D, H, W)
        x = F.transpose(x, (0, 1, 3, 4, 5, 2))        # (N, C*f1*f2, D, H, W, f3)
        x = F.reshape(x, (0, 0, 0, 0, -3))            # (N, C*f1*f2, D, H, W*f3)
        x = F.reshape(x, (0, -4, -1, f2, 0, 0, 0))    # (N, C*f1, f2, D, H, W*f3)
        x = F.transpose(x, (0, 1, 3, 4, 2, 5))        # (N, C*f1, D, H, f2, W*f3)
        x = F.reshape(x, (0, 0, 0, -3, 0))            # (N, C*f1, D, H*f2, W*f3)
        x = F.reshape(x, (0, -4, -1, f1, 0, 0, 0))    # (N, C, f1, D, H*f2, W*f3)
        x = F.transpose(x, (0, 1, 3, 2, 4, 5))        # (N, C, D, f1, H*f2, W*f3)
        x = F.reshape(x, (0, 0, -3, 0, 0))            # (N, C, D*f1, H*f2, W*f3)
        return x
