"""contrib recurrent cells (parity:
`python/mxnet/gluon/contrib/rnn/rnn_cell.py` — VariationalDropoutCell:27,
LSTMPCell:198)."""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell, ModifierCell, BidirectionalCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (locked) dropout: ONE dropout mask per unroll, reused at
    every time step, applied to inputs/states/outputs (reference
    rnn_cell.py:27; Gal & Ghahramani recipe)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        assert not drop_states or not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support variational state dropout; " \
            "wrap the cells underneath instead."
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        super().__init__(base_cell)
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _initialize_input_masks(self, F, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(F.ones_like(states[0]),
                                              p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(F.ones_like(inputs),
                                              p=self.drop_inputs)

    def _initialize_output_mask(self, F, output):
        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(F.ones_like(output),
                                               p=self.drop_outputs)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        self._initialize_input_masks(F, inputs, states)
        if self.drop_states:
            states = list(states)
            # reference drops only the first state (the hidden h)
            states[0] = F.elemwise_mul(states[0], self.drop_states_mask)
        if self.drop_inputs:
            inputs = F.elemwise_mul(inputs, self.drop_inputs_mask)
        next_output, next_states = cell(inputs, states)
        self._initialize_output_mask(F, next_output)
        if self.drop_outputs:
            next_output = F.elemwise_mul(next_output,
                                         self.drop_outputs_mask)
        return next_output, next_states


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a linear recurrent projection (reference rnn_cell.py:198;
    Sak et al. 2014): h_t = W_r (o * tanh(c_t)) — the recurrent/hidden
    state is the lower-dim projection."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = F.elemwise_add(i2h, h2h, name=prefix + "plus0")
        sl = F.SliceChannel(gates, num_outputs=4, name=prefix + "slice")
        in_gate = F.Activation(sl[0], act_type="sigmoid", name=prefix + "i")
        forget_gate = F.Activation(sl[1], act_type="sigmoid",
                                   name=prefix + "f")
        in_transform = F.Activation(sl[2], act_type="tanh", name=prefix + "c")
        out_gate = F.Activation(sl[3], act_type="sigmoid", name=prefix + "o")
        next_c = F.elemwise_add(
            F.elemwise_mul(forget_gate, states[1], name=prefix + "mul0"),
            F.elemwise_mul(in_gate, in_transform, name=prefix + "mul1"),
            name=prefix + "state")
        hidden = F.elemwise_mul(
            out_gate, F.Activation(next_c, act_type="tanh"),
            name=prefix + "hidden")
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size,
                                  name=prefix + "out")
        return next_r, [next_r, next_c]
