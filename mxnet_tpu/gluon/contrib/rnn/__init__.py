"""gluon.contrib.rnn (parity: `python/mxnet/gluon/contrib/rnn/`)."""
from .conv_rnn_cell import *  # noqa: F401,F403
from .rnn_cell import *       # noqa: F401,F403
from . import conv_rnn_cell   # noqa: F401
from . import rnn_cell        # noqa: F401
