"""Convolutional recurrent cells (parity:
`python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py` — Conv{1,2,3}D{RNN,LSTM,
GRU}Cell): the i2h/h2h projections are convolutions over spatial feature
maps instead of dense matmuls; states are (batch, channels, *spatial).

`input_shape` is (channels, *spatial) and is REQUIRED (as in the
reference): state spatial dims derive from it statically, which is also
exactly what XLA wants."""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell
from ....base import MXNetError

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    assert len(v) == n
    return tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared conv-cell machinery (reference conv_rnn_cell.py:37)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation
        self._dims = dims

        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError(
                    f"h2h_kernel must be odd to preserve spatial dims, got "
                    f"{self._h2h_kernel} (reference conv_rnn_cell.py:68)")
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_dilate = _tup(h2h_dilate, dims)
        # h2h 'same' padding so the state spatial dims are preserved
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))

        in_channels = self._input_shape[0]
        spatial = self._input_shape[1:]
        self._state_spatial = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad, self._i2h_dilate,
                                  self._i2h_kernel))

        ng = self._num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(ng * hidden_channels, in_channels)
            + self._i2h_kernel, init=i2h_weight_initializer,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(ng * hidden_channels, hidden_channels)
            + self._h2h_kernel, init=h2h_weight_initializer,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_channels,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_channels,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    @property
    def _num_gates(self):
        raise NotImplementedError

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._state_spatial
        return [{"shape": shape, "__layout__": self._conv_layout}
                for _ in range(self._num_states)]

    def _conv_pair(self, F, inputs, state, i2h_weight, h2h_weight, i2h_bias,
                   h2h_bias):
        prefix = f"t{self._counter}_"
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate,
                            num_filter=ng * self._hidden_channels,
                            name=prefix + "i2h")
        h2h = F.Convolution(state, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate,
                            num_filter=ng * self._hidden_channels,
                            name=prefix + "h2h")
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    _num_states = 1

    @property
    def _num_gates(self):
        return 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_pair(F, inputs, states[0], i2h_weight,
                                   h2h_weight, i2h_bias, h2h_bias)
        out = self._get_activation(F, F.elemwise_add(i2h, h2h),
                                   self._activation)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_states = 2

    @property
    def _num_gates(self):
        return 4

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_pair(F, inputs, states[0], i2h_weight,
                                   h2h_weight, i2h_bias, h2h_bias)
        gates = F.elemwise_add(i2h, h2h)
        sl = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = self._get_activation(F, sl[2], self._activation)
        o = F.Activation(sl[3], act_type="sigmoid")
        next_c = F.elemwise_add(F.elemwise_mul(f, states[1]),
                                F.elemwise_mul(i, g))
        next_h = F.elemwise_mul(o, self._get_activation(F, next_c,
                                                        self._activation))
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_states = 1

    @property
    def _num_gates(self):
        return 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_pair(F, inputs, states[0], i2h_weight,
                                   h2h_weight, i2h_bias, h2h_bias)
        i2h_sl = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_sl = F.SliceChannel(h2h, num_outputs=3, axis=1)
        r = F.Activation(F.elemwise_add(i2h_sl[0], h2h_sl[0]),
                         act_type="sigmoid")
        z = F.Activation(F.elemwise_add(i2h_sl[1], h2h_sl[1]),
                         act_type="sigmoid")
        n = self._get_activation(
            F, F.elemwise_add(i2h_sl[2], F.elemwise_mul(r, h2h_sl[2])),
            self._activation)
        one = F.ones_like(z)
        out = F.elemwise_add(
            F.elemwise_mul(z, states[0]),
            F.elemwise_mul(F.elemwise_sub(one, z), n))
        return out, [out]


def _make(base, dims, doc_name, ref_line):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=None, activation="tanh", prefix=None,
                 params=None):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                      i2h_weight_initializer, h2h_weight_initializer,
                      i2h_bias_initializer, h2h_bias_initializer,
                      dims, conv_layout or "NC" + "DHW"[3 - dims:],
                      activation, prefix, params)

    cls = type(doc_name, (base,), {
        "__init__": __init__,
        "__doc__": f"{doc_name} (reference conv_rnn_cell.py:{ref_line}).",
    })
    return cls


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell", 218)
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell", 285)
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell", 352)
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell", 473)
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell", 545)
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell", 617)
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell", 738)
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell", 805)
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell", 872)
