"""gluon.contrib (parity `python/mxnet/gluon/contrib/__init__.py`):
layer containers + SyncBatchNorm (nn), Conv*RNN / VariationalDropout /
LSTMP cells (rnn) — SURVEY.md §2.3."""
from . import nn   # noqa: F401
from . import rnn  # noqa: F401
