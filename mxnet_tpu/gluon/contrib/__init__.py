"""gluon.contrib (parity `python/mxnet/gluon/contrib/__init__.py`).

Populated as contrib pieces land (sync BN wrapper, Conv*RNN cells,
VariationalDropoutCell — SURVEY.md §2.3).
"""
try:
    from . import nn  # noqa: F401
    from . import rnn  # noqa: F401
    from . import data  # noqa: F401
except ImportError:  # pragma: no cover - during staged build only
    pass
