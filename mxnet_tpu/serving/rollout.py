"""Zero-downtime weight rollout — versioned train→serve checkpoint
streaming over a watched directory.

A fleet serving live traffic has to take checkpoint updates without
tearing anything down: tearing down a :class:`~.predictor.Predictor` or
:class:`~.generation.engine.GenerationEngine` means dropped requests,
cold compiles and a dead KV slab. This module closes the train→serve
loop instead:

* **publish** (:func:`publish`, hooked into ``model.save_checkpoint``
  via :func:`publish_checkpoint` when ``MXNET_ROLLOUT_DIR`` is set) —
  one CRC-footed payload file per version (``nd.save``: every array
  carries the PR 1 crc32/length footer) holding ``arg:``/``aux:``/
  ``draft:``-prefixed entries, gathered to REPLICATED host arrays first
  (a ZeRO-1/SPMD training fleet's shards must become one portable file
  before serving ever sees them), then a version-tagged JSON manifest
  written temp-then-``durable_replace`` — a reader sees the old
  manifest set or the new one, never a torn file. Idempotent: a
  re-publish of an existing version is a counted no-op.
* **subscribe** (:class:`RolloutSubscriber` /
  :class:`RolloutWatcher`) — poll the directory every
  ``MXNET_ROLLOUT_POLL_S``, ingest the newest unseen version into a
  refcounted :class:`WeightSet` (CRC-verified by ``nd.load``), and
  REJECT-and-keep-serving on a torn manifest, a corrupt payload or a
  stale/duplicate version stamp — each rejection journaled
  (``rollout_reject``) and counted (``rollout.reject_<reason>``), all
  three fault-injectable through the ``publish`` point of
  ``MXNET_FAULT_SPEC``.
* **swap** — the serving stacks flip to a WeightSet atomically between
  batch flushes / engine ticks (``Predictor.swap_weights`` /
  ``GenerationEngine.swap_weights``) as pure buffer substitution into
  already-warmed executables: identical shapes/dtypes, zero steady-state
  compiles. ``GenerationRouter.rolling_swap`` rolls a fleet one replica
  at a time behind the PR 11 burn gate (``MXNET_ROLLOUT_SLO_GATE``)
  with automatic journaled rollback to the pinned previous version.

Telemetry rides ``rollout.*`` (publishes, ingests, rejects by reason,
rollbacks, the ``rollout.version`` gauge); the health journal carries
``rollout_publish`` / ``rollout_reject`` / ``rollout_swap`` /
``rollout_rollback`` / ``rollout_drained`` events.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time

import numpy as np

from .. import analysis
from .. import health
from .. import ndarray as nd
from .. import telemetry
from ..base import MXNetError, getenv, register_env
from ..log import get_logger
from ..resilience import CorruptCheckpointError, durable_replace, inject

__all__ = ["WeightSet", "RolloutSubscriber", "RolloutWatcher",
           "RolloutError", "publish", "publish_checkpoint",
           "list_versions"]

register_env("MXNET_ROLLOUT_DIR", "",
             "weight-rollout directory: save_checkpoint publishes each "
             "epoch there as a versioned WeightSet (CRC-footed payload + "
             "atomic manifest) and serving subscribers hot-swap to it; "
             "empty disables the train->serve publisher hook")
register_env("MXNET_ROLLOUT_POLL_S", 2.0,
             "seconds between rollout-directory polls of a "
             "RolloutWatcher subscriber thread")
register_env("MXNET_ROLLOUT_SLO_GATE", 1.0,
             "rolling_swap burn gate: after each replica flips, a short-"
             "window SLO burn rate above this triggers automatic "
             "journaled rollback of the whole fleet to the pinned "
             "previous version")
register_env("MXNET_ROLLOUT_KEEP", 4,
             "retain only the newest K published versions in the rollout "
             "directory (payload + manifest pairs; 0 = keep all)")

_PAYLOAD_FMT = "v%06d.params"
_MANIFEST_FMT = "v%06d.manifest.json"
_MANIFEST_RE = re.compile(r"^v(\d{6,})\.manifest\.json$")


def _logger():
    return get_logger("mxnet_tpu.serving.rollout")


class RolloutError(MXNetError):
    """A publish could not complete (IO fault, bad version)."""


def _host(v):
    """Gather one parameter to a replicated host array: ``asnumpy`` for
    NDArrays, ``np.asarray`` for jax arrays (which materializes — and
    thereby gathers — a sharded Array's global value)."""
    return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)


class WeightSet:
    """One published weight version: replicated host copies of the arg /
    aux (and optional speculative-draft) parameters, refcounted so a
    version stays pinned while any serving stack still reads it (live
    generation sessions drain on their admission-time version)."""

    def __init__(self, version, arg_params, aux_params=None,
                 draft_params=None, source=""):
        self.version = int(version)
        self.arg_params = {str(k): _host(v)
                           for k, v in dict(arg_params or {}).items()}
        self.aux_params = {str(k): _host(v)
                           for k, v in dict(aux_params or {}).items()}
        self.draft_params = {str(k): _host(v)
                             for k, v in dict(draft_params or {}).items()}
        self.source = source
        self._refs = 1                # creator's reference
        self._lock = analysis.make_lock("serving.rollout.weightset")

    @property
    def refs(self):
        with self._lock:
            return self._refs

    def acquire(self):
        with self._lock:
            self._refs += 1
        return self

    def release(self):
        """Drop one reference; returns True when the set just became
        unreferenced (fully drained everywhere)."""
        with self._lock:
            self._refs = max(self._refs - 1, 0)
            return self._refs == 0

    def nbytes(self):
        return sum(a.nbytes for params in
                   (self.arg_params, self.aux_params, self.draft_params)
                   for a in params.values())

    def __repr__(self):
        return (f"WeightSet(version={self.version}, "
                f"arrays={len(self.arg_params) + len(self.aux_params) + len(self.draft_params)}, "
                f"refs={self.refs})")


# ---------------------------------------------------------------------------
# Publish
# ---------------------------------------------------------------------------


def list_versions(rollout_dir):
    """Sorted version numbers with a manifest file in ``rollout_dir``
    (filename-level: a fault-stamped stale manifest still counts as its
    filename's version here — content validation is the subscriber's)."""
    try:
        names = os.listdir(str(rollout_dir))
    except OSError:
        return []
    return sorted(int(m.group(1))
                  for m in map(_MANIFEST_RE.match, names) if m)


def publish(rollout_dir, version, arg_params, aux_params=None,
            draft_params=None, source=""):
    """Atomically publish one weight version into ``rollout_dir``:
    gather every parameter to a replicated host array, write the
    CRC-footed payload (``nd.save`` — synced before the manifest so the
    manifest can never point at an unfinished file), then the JSON
    manifest temp-then-rename. Returns the manifest path, or None when
    ``version`` is already published (idempotent double-publish no-op).

    The ``publish`` fault point of ``MXNET_FAULT_SPEC`` covers the whole
    operation: errno rules raise here; ``truncate=K`` tears the manifest
    at K bytes (torn rename); ``error=CORRUPT`` flips a payload byte
    after the CRC footers are written; ``error=STALE`` stamps the
    manifest with an already-published version number — the three
    publish pathologies the subscriber must reject."""
    rollout_dir = str(rollout_dir)
    version = int(version)
    if version < 0:
        raise RolloutError(f"rollout version must be >= 0, got {version}")
    os.makedirs(rollout_dir, exist_ok=True)
    manifest_path = os.path.join(rollout_dir, _MANIFEST_FMT % version)
    if os.path.exists(manifest_path):
        if telemetry._enabled:
            telemetry.counter("rollout.publish_duplicate").inc()
        _logger().info("rollout: version %d already published, no-op",
                       version)
        return None
    t0 = time.perf_counter()
    # the fault hook may raise (errno rules) or hand back a rule whose
    # CORRUPT/STALE/truncate payload this writer enacts on itself
    rule = inject("publish", manifest_path)
    mode = getattr(rule, "error", None) if rule is not None else None
    torn = getattr(rule, "truncate", None) if rule is not None else None

    save_dict = {}
    for prefix, params in (("arg", arg_params), ("aux", aux_params),
                           ("draft", draft_params)):
        for k, v in dict(params or {}).items():
            save_dict[f"{prefix}:{k}"] = nd.array(_host(v))
    if not save_dict:
        raise RolloutError("publish needs at least one parameter")
    payload = _PAYLOAD_FMT % version
    payload_path = os.path.join(rollout_dir, payload)
    nd.save(payload_path, save_dict)
    from .. import engine

    if engine.async_io_enabled():
        # the manifest is the commit point: the payload bytes must be
        # durably complete before any reader can learn the file exists
        engine.wait_all()
    if mode == "CORRUPT":
        with open(payload_path, "r+b") as f:
            off = max(os.path.getsize(payload_path) // 2, 32)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([(b[0] if b else 0) ^ 0xFF]))
        _logger().warning("fault injection: corrupted payload byte of %s",
                          payload_path)
    stamped = version
    if mode == "STALE":
        prior = [v for v in list_versions(rollout_dir) if v < version]
        stamped = prior[-1] if prior else version
        _logger().warning("fault injection: stamping manifest %s with "
                          "stale version %d", manifest_path, stamped)
    doc = json.dumps({"version": stamped, "payload": payload,
                      "arrays": len(save_dict), "source": str(source),
                      "created_unix": time.time()}, indent=0)
    if torn is not None:
        doc = doc[:torn]
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    durable_replace(tmp, manifest_path)
    _retain(rollout_dir)
    if telemetry._enabled:
        telemetry.counter("rollout.publishes").inc()
        telemetry.gauge("rollout.published_version").set(version)
        telemetry.histogram("rollout.publish_us").record(
            (time.perf_counter() - t0) * 1e6)
    if health._enabled:
        health.event("rollout_publish", version=version,
                     arrays=len(save_dict), source=str(source))
    _logger().info("rollout: published version %d (%d arrays) to %s",
                   version, len(save_dict), rollout_dir)
    return manifest_path


def _retain(rollout_dir, keep=None):
    """Drop all but the newest ``keep`` published versions (manifest +
    payload pairs); 0 keeps everything — same retention contract as
    ``MXNET_CHECKPOINT_KEEP``."""
    keep = int(getenv("MXNET_ROLLOUT_KEEP") if keep is None else keep)
    if keep <= 0:
        return
    for v in list_versions(rollout_dir)[:-keep]:
        for name in (_MANIFEST_FMT % v, _PAYLOAD_FMT % v):
            try:
                os.remove(os.path.join(rollout_dir, name))
            except OSError:
                pass


def publish_checkpoint(prefix, epoch, arg_params, aux_params=None,
                       rollout_dir=None):
    """The ``save_checkpoint`` publisher hook: publish epoch ``epoch`` as
    rollout version ``epoch`` when ``MXNET_ROLLOUT_DIR`` is set (no-op
    otherwise). Publish failures are logged and counted but NEVER
    raised — a sick serving directory must not kill the training loop
    that is trying to checkpoint."""
    rollout_dir = (getenv("MXNET_ROLLOUT_DIR") if rollout_dir is None
                   else rollout_dir)
    if not str(rollout_dir or "").strip():
        return None
    try:
        return publish(rollout_dir, epoch, arg_params, aux_params,
                       source=f"{prefix}@{int(epoch)}")
    except Exception as e:  # noqa: BLE001 — training survives publish faults
        if telemetry._enabled:
            telemetry.counter("rollout.publish_errors").inc()
        if health._enabled:
            health.event("rollout_publish_error", version=int(epoch),
                         error=repr(e))
        _logger().error("rollout: publish of epoch %s failed (training "
                        "continues): %r", epoch, e)
        return None


# ---------------------------------------------------------------------------
# Subscribe
# ---------------------------------------------------------------------------


def _load_weightset(payload_path, version):
    """CRC-verified ingest of one payload file into a WeightSet (the PR 1
    footer walk inside ``nd.load`` raises ``CorruptCheckpointError`` on
    any flipped byte)."""
    arg, aux, draft = {}, {}, {}
    for k, v in nd.load(payload_path).items():
        kind, _, name = k.partition(":")
        {"arg": arg, "aux": aux, "draft": draft}.get(kind, arg)[name] = v
    return WeightSet(version, arg, aux, draft, source=payload_path)


class RolloutSubscriber:
    """Poll-driven ingest side of the rollout directory: ``poll()``
    returns a freshly ingested :class:`WeightSet` (the NEWEST unseen
    valid version) or None. Every invalid manifest is rejected exactly
    once — torn JSON, stale/duplicate version stamp, corrupt-CRC
    payload — with the subscriber (and whatever it feeds) continuing to
    serve the current version; that reject-and-keep-serving path is what
    the ``publish`` fault rules exercise."""

    def __init__(self, rollout_dir, current_version=0):
        self._dir = str(rollout_dir)
        self.version = int(current_version)
        self._handled = set()         # manifest filenames ingested/rejected

    def _reject(self, name, reason, exc=None, version=None):
        self._handled.add(name)
        if telemetry._enabled:
            telemetry.counter("rollout.rejects").inc()
            telemetry.counter(f"rollout.reject_{reason}").inc()
        if health._enabled:
            health.event("rollout_reject", manifest=name, reason=reason,
                         version=version, serving=self.version,
                         **({"error": repr(exc)} if exc is not None else {}))
        _logger().warning(
            "rollout: rejected %s (%s%s); still serving version %d",
            name, reason, f": {exc!r}" if exc is not None else "",
            self.version)

    def poll(self):
        """One directory sweep. Returns the ingested WeightSet for the
        newest unseen valid version, or None (nothing new, or everything
        new was rejected)."""
        try:
            names = sorted(os.listdir(self._dir))
        except OSError:
            return None
        fresh = []
        for name in names:
            m = _MANIFEST_RE.match(name)
            if m is None or name in self._handled:
                continue
            path = os.path.join(self._dir, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
                version = int(doc["version"])
                payload = str(doc["payload"])
            except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
                self._reject(name, "torn_manifest", e)
                continue
            if version <= self.version:
                # a NEW manifest file stamping an old (or the current)
                # version — the stale/duplicate publish pathology
                self._reject(name, "stale_version", version=version)
                continue
            fresh.append((version, name, payload))
        for version, name, payload in sorted(fresh, reverse=True):
            try:
                ws = _load_weightset(os.path.join(self._dir, payload),
                                     version)
            except (MXNetError, OSError) as e:
                reason = ("corrupt_crc"
                          if isinstance(e, CorruptCheckpointError)
                          else "unreadable_payload")
                self._reject(name, reason, e, version=version)
                continue
            self._handled.add(name)
            # versions skipped over by this ingest are handled silently —
            # they were valid, just superseded within one poll window
            for v, n, _ in fresh:
                if v < version:
                    self._handled.add(n)
            self.version = version
            if telemetry._enabled:
                telemetry.counter("rollout.ingests").inc()
                telemetry.gauge("rollout.version").set(version)
            if health._enabled:
                health.event("rollout_ingest", version=version,
                             manifest=name)
            _logger().info("rollout: ingested version %d from %s",
                           version, name)
            return ws
        return None


class RolloutWatcher:
    """Background subscriber thread: polls every ``MXNET_ROLLOUT_POLL_S``
    and hands each ingested WeightSet to ``apply`` (e.g. a router's
    ``rolling_swap`` or an engine's ``swap_weights``). Apply failures are
    logged and the watcher keeps polling — the serving side never dies
    because a publish was bad."""

    def __init__(self, rollout_dir, apply, poll_s=None, current_version=0,
                 start=True):
        self._apply = apply
        self._poll_s = float(getenv("MXNET_ROLLOUT_POLL_S")
                             if poll_s is None else poll_s)
        self.subscriber = RolloutSubscriber(rollout_dir, current_version)
        self._stop = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="mxnet_tpu.serving.rollout.watch")
            self._thread.start()

    def poll_once(self):
        """One manual poll+apply step (tests, start=False watchers)."""
        ws = self.subscriber.poll()
        if ws is None:
            return None
        try:
            self._apply(ws)
        except Exception as e:  # noqa: BLE001 — keep serving, keep polling
            if telemetry._enabled:
                telemetry.counter("rollout.apply_errors").inc()
            _logger().error("rollout: applying version %d failed: %r",
                            ws.version, e)
        return ws

    def _loop(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self._poll_s)

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
