"""DynamicBatcher — coalesce concurrent requests into padded bucket batches.

The economics of accelerator inference: one request of 3 rows and one of
5 cost the same single dispatch as their 8-row union, so under concurrent
traffic the scheduler's job is to *merge* callers, not interleave them.
This batcher is the serving subsystem's scheduler:

* callers ``submit()`` individual requests (any row count) and get a
  ``concurrent.futures.Future``;
* one worker thread pops rows FIFO from the
  :class:`~mxnet_tpu.serving.admission.AdmissionQueue` when either enough
  rows queue up to fill the largest bucket or the oldest request has
  waited ``MXNET_SERVING_MAX_WAIT_MS`` — latency is bounded by *your own*
  wait budget, throughput by how full the flush was
  (``serving.batch_fill_ratio``). The request at the batch boundary is
  SPLIT so a max-batch flush is exactly full (its tail keeps the queue
  head); oversize requests stream through the same mechanism, max_batch
  rows per flush;
* the coalesced rows are concatenated, padded up to the smallest bucket
  that fits (``io.pad_arrays``), computed ONCE, and sliced back per
  request — pieces of a split request are reassembled in row order, so
  each caller receives exactly its own rows.

Failure semantics: expired requests are failed with
:class:`DeadlineExceededError` *before* compute; transient executor errors
(``Predictor.retry_on``, default ``OSError``) are retried with
``resilience.retry_call`` backoff but NEVER past the earliest deadline in
the batch; non-transient errors fail every request in the batch with the
original exception. ``close()`` drains: admitted requests complete, new
ones are rejected with :class:`ServerClosedError`.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from .. import analysis
from .. import ndarray as nd
from .. import observatory
from .. import telemetry
from .. import tracing
from ..io import staging as _staging
from ..base import getenv, register_env
from ..log import get_logger
from ..resilience import retry_call
from .admission import AdmissionQueue, DeadlineExceededError, Request
from .health import attach_batcher, queue_ready

__all__ = ["DynamicBatcher"]

register_env("MXNET_SERVING_MAX_WAIT_MS", 5.0,
             "dynamic micro-batcher flush deadline: a queued request waits "
             "at most this long for co-batchable traffic before its batch "
             "is flushed short")


class DynamicBatcher:
    """Queue-and-coalesce front end over a :class:`Predictor`.

    Parameters
    ----------
    predictor : Predictor
        The bucket-bound engine; its largest bucket is the coalescing
        target (``max_batch``).
    max_wait_ms : float, optional
        Flush deadline override (default ``MXNET_SERVING_MAX_WAIT_MS``).
    max_queue : int, optional
        Admission bound override (default ``MXNET_SERVING_MAX_QUEUE``).
    retries / backoff_s :
        Transient-failure retry budget handed to ``resilience.retry_call``
        (what counts as transient is ``predictor.retry_on``).
    """

    def __init__(self, predictor, max_wait_ms=None, max_queue=None,
                 retries=2, backoff_s=0.02):
        self._predictor = predictor
        wait_ms = (getenv("MXNET_SERVING_MAX_WAIT_MS")
                   if max_wait_ms is None else max_wait_ms)
        self._max_wait_s = float(wait_ms) / 1e3
        self._max_batch = predictor.max_batch
        self._admission = AdmissionQueue(max_queue)
        self._retries = retries
        self._backoff_s = backoff_s
        self._logger = get_logger("mxnet_tpu.serving")
        # one assisting caller at a time; piece reassembly of split
        # requests is then reachable from two runner threads, so delivery
        # state is guarded by _result_lock
        self._assist = analysis.make_lock("serving.batcher.assist")
        self._result_lock = analysis.make_lock("serving.batcher.result")
        self._worker = threading.Thread(
            target=self._loop, daemon=True, name="mxnet_tpu.serving.batcher")
        self._worker.start()
        # fleet health: /healthz watches the worker thread, /readyz the
        # queue watermark + warmup state (construction-time registration)
        self.health_name = attach_batcher(self)

    # -- client API ----------------------------------------------------------

    @property
    def predictor(self):
        return self._predictor

    @property
    def queue_depth(self):
        return len(self._admission)

    def healthy(self):
        """Liveness: (ok, detail) — False only when the worker thread
        died while the batcher still accepts work."""
        if not self._worker.is_alive() and not self._admission.closed:
            return False, "batcher worker thread died"
        return True, "ok"

    def ready(self):
        """Readiness: (ok, reason) — closed/draining, predictor not yet
        warmed, or intake queue above the health watermark all report
        not-ready (the /readyz probe)."""
        if self._admission.closed:
            return False, "closed (draining)"
        p = self._predictor
        # traffic-compiled predictors count as warmed (the engine rule)
        if not getattr(p, "_warmed", True) and not getattr(p, "_execs", True):
            return False, "predictor warmup not run"
        return queue_ready(self._admission)

    def submit(self, data, timeout=None, tenant=None):
        """Enqueue one request; returns a Future resolving to the same
        value ``predictor.predict(data)`` would. ``timeout`` (seconds)
        sets the request deadline: expire in queue (or before a retry) and
        the future fails with :class:`DeadlineExceededError`. ``tenant``
        names the QoS tenant (class/quota per ``MXNET_QOS_SPEC``; ignored
        while QoS is off — the queue then also raises
        :class:`~mxnet_tpu.serving.qos.QuotaExceededError`
        synchronously). Raises :class:`QueueFullError` /
        :class:`ServerClosedError` synchronously. Any row count is
        accepted — requests larger than the biggest bucket stream through
        successive batches and reassemble."""
        arrays = self._predictor._as_arrays(data)
        n = int(arrays[0].shape[0])
        deadline = (time.monotonic() + float(timeout)
                    if timeout is not None else None)
        return self._submit_one(arrays, n, deadline, tenant=tenant)

    def predict(self, data, timeout=None, tenant=None):
        """Blocking convenience: ``submit(...).result()`` — with
        CALLER-RUNS assistance. A blocking caller that finds the assist
        slot free drains queued batches inline (its own plus whatever
        coalesced behind it) instead of paying two thread handoffs to the
        worker; under tiny per-batch compute the handoffs, not the math,
        dominate latency (the GIL hands off in multi-ms quanta). Async
        ``submit()`` traffic keeps the worker + flush-window path."""
        fut = self.submit(data, timeout=timeout, tenant=tenant)
        if self._assist.acquire(blocking=False):
            self._admission.assist_active = True
            try:
                while not fut.done():
                    batch, reason = self._admission.get_batch_nowait(
                        self._max_batch)
                    if batch is None:
                        break  # our request is mid-compute on the worker
                    self._run_batch_guarded(batch, reason)
            finally:
                self._admission.assist_active = False
                self._assist.release()
                self._admission.kick()  # anything left is the worker's
        return fut.result()

    def warmup(self, buckets=None):
        """Compile-ahead every bucket — see :func:`mxnet_tpu.serving.warmup`."""
        from .warmup import warmup

        return warmup(self._predictor, buckets=buckets)

    def close(self, timeout=None):
        """Graceful drain: stop admission, let the worker finish every
        already-accepted request, join it. Idempotent. Deregisters the
        health probes — a deliberately closed batcher must not pin
        ``/readyz``."""
        self._admission.close()
        if self._worker.is_alive():
            self._worker.join(timeout)
        from .. import health

        health.unregister(self.health_name)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- worker --------------------------------------------------------------

    def _submit_one(self, arrays, rows, deadline, tenant=None):
        fut = Future()
        req = Request(arrays, rows, fut, deadline=deadline, tenant=tenant)
        if tracing._enabled:
            # root span of this request's trace — finished by the thread
            # that resolves the future (worker, assisting caller, or this
            # thread on synchronous rejection)
            req.span = tracing.begin("serving.request", cat="serving",
                                     rows=rows)
            sub = req.span.child("serving.admission")
            # flow arrow from this submit slice to the batch that will
            # compute the request (flow_end in _run_batch). Emitted BEFORE
            # put(): once put() releases the request, the worker can emit
            # the flow_end first and the arrow's end would precede its
            # start; a dangling start on a rejected put is harmless
            tracing.flow_start(req.span.span_id, name="serving.request")
            try:
                self._admission.put(req)
            except Exception as e:
                sub.set(error=repr(e)).finish()
                req.span.set(error=repr(e)).finish()
                raise
            sub.finish()
        else:
            self._admission.put(req)
        if telemetry._enabled:
            telemetry.counter("serving.requests").inc()
        return fut

    def _loop(self):
        # overlap lane (MXNET_OVERLAP=1): while a flush executes on
        # device, the worker preps the NEXT one — `_execute_prep` calls
        # `_stage_next` between forward dispatch and drain, so the staged
        # prep's concat/pad/placement rides under the in-flight compute.
        # A staged prep is executed on the next loop turn (after a
        # deadline re-sweep); MXNET_OVERLAP=0 never stages.
        staged = None
        while True:
            if staged is not None:
                prep, staged = staged, None
                prep = self._resweep_staged(prep)
                if prep is None:
                    continue
                staged = self._execute_prep_guarded(prep, stage=True)
                continue
            batch, reason = self._admission.get_batch(
                self._max_batch, self._max_wait_s)
            if batch is None:
                return
            staged = self._run_batch_guarded(batch, reason, stage=True)

    def _run_batch_guarded(self, batch, reason, stage=None):
        """_run_batch with the never-strand guarantee: an unexpected bug in
        the batching/delivery path fails every popped future instead of
        killing the worker — or, on the assist path, instead of leaking
        batch-mates' futures (popped, so no one else would run them) while
        the exception propagates to the one assisting caller. Returns the
        prep staged mid-flight, if any (worker loop only; the assist path
        never stages — it is a borrowed caller thread)."""
        try:
            return self._run_batch(batch, reason, stage=stage)
        except Exception as e:  # noqa: BLE001
            for r in batch:
                if not r.origin.future.done():
                    self._fail(r, e)
            self._logger.error("serving batch failed unexpectedly: %r", e)
            return None

    def _execute_prep_guarded(self, prep, stage=None):
        """Never-strand wrapper for executing an already-prepared flush."""
        try:
            return self._execute_prep(prep, stage=stage)
        except Exception as e:  # noqa: BLE001
            for r in prep["live"]:
                if not r.origin.future.done():
                    self._fail(r, e)
            self._logger.error("serving batch failed unexpectedly: %r", e)
            return None

    def _fail(self, req, exc, timeout=False):
        """Fail the request a piece belongs to (once — later pieces of a
        split request are dropped unrun by the queue's done() check)."""
        orig = req.origin
        with self._result_lock:
            if orig.future.done():
                return
            if telemetry._enabled:
                telemetry.counter(
                    "serving.timeouts" if timeout else "serving.errors").inc()
            orig.future.set_exception(exc)
            if orig.span is not None:
                orig.span.set(error=repr(exc), timeout=timeout).finish()

    def _deliver(self, req, sliced, done_ts):
        """Hand a computed piece its rows; a split request resolves once
        every piece has arrived, reassembled in row order. Pieces may be
        delivered by the worker AND an assisting caller, so the
        accumulation is lock-guarded."""
        orig = req.origin
        with self._result_lock:
            if orig.future.done():
                return
            t0r = (tracing.now_us()
                   if tracing._enabled and orig.span is not None else None)
            if req.offset == 0 and req.rows == orig.total_rows:
                orig.future.set_result(self._predictor._wrap_outputs(sliced))
            else:
                if orig.parts is None:
                    orig.parts = []
                orig.parts.append((req.offset, req.rows, sliced))
                if sum(r for _, r, _ in orig.parts) < orig.total_rows:
                    return
                orig.parts.sort(key=lambda p: p[0])
                merged = [nd.concatenate([p[2][k] for p in orig.parts],
                                         axis=0)
                          for k in range(len(sliced))]
                orig.parts = None
                orig.future.set_result(self._predictor._wrap_outputs(merged))
            if t0r is not None:
                # the request resolved on THIS thread: close its span tree
                # (queue + execute spans were emitted by the batch runner)
                tracing.emit_span("serving.reassembly", t0r,
                                  tracing.now_us() - t0r, cat="serving",
                                  parent=orig.span, rows=orig.total_rows)
                orig.span.finish()
            if telemetry._enabled:
                telemetry.histogram("serving.e2e_us").record(
                    (done_ts - orig.enqueued_at) * 1e6)

    def _run_batch(self, reqs, reason, stage=None):
        prep = self._prepare_batch(reqs, reason)
        if prep is None:
            return None
        return self._execute_prep(prep, stage=stage)

    def _prepare_batch(self, reqs, reason, staged=False, requeued=False):
        """Everything host-side a flush needs BEFORE dispatch: deadline
        filter, queue telemetry/spans, feed concat — and, for a staged
        prep (overlap lane), the pad up to the bucket, so the predictor's
        own pad is a no-op and the transfer happened off the critical
        path. Returns a prep dict or None when nothing stayed live."""
        tele = telemetry._enabled
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.deadline is not None and now >= r.deadline:
                self._fail(r, DeadlineExceededError(
                    f"request waited {now - r.enqueued_at:.3f}s in queue, "
                    "past its deadline"), timeout=True)
            elif not r.origin.future.done():
                live.append(r)
        if not live:
            return None
        if tele and not requeued:
            for r in live:
                telemetry.histogram("serving.time_in_queue_us").record(
                    (now - r.enqueued_at) * 1e6)
        rows = sum(r.rows for r in live)
        bucket = self._predictor.bucket_for(rows)
        if tracing._enabled:
            # per-request queue spans (submit -> this pop) + the flow
            # arrow landing in this batch's slice
            t_pop = tracing.now_us()
            for r in live:
                sp = r.origin.span
                if sp is None:
                    continue
                if not r.traced_queue:
                    r.traced_queue = True
                    tracing.emit_span("serving.queue", sp.t0,
                                      t_pop - sp.t0, cat="serving",
                                      parent=sp, offset=r.offset,
                                      rows=r.rows)
                if not r.origin.flow_ended:
                    # one arrow per REQUEST: split pieces share the
                    # origin's flow id, so only the first batch a
                    # request lands in terminates the flow
                    r.origin.flow_ended = True
                    tracing.flow_end(sp.span_id, name="serving.request")
        feeds = []
        for i in range(len(self._predictor.data_names)):
            parts = [r.arrays[i] for r in live]
            feeds.append(parts[0] if len(parts) == 1
                         else nd.concatenate(parts, axis=0))
        if staged:
            from ..io.io import pad_arrays

            feeds, _ = pad_arrays(feeds, bucket)
        earliest = min((r.deadline for r in live
                        if r.deadline is not None), default=None)
        return {"live": live, "reason": reason, "rows": rows,
                "bucket": bucket, "feeds": feeds, "earliest": earliest,
                "staged": staged, "t0": time.perf_counter()}

    def _resweep_staged(self, prep):
        """A staged prep sat out one flush: re-sweep its deadlines before
        dispatch. Expired requests fail here; survivors are re-prepared
        (their rows no longer pad the batch) exactly like the post-timeout
        re-run in `_execute_prep`."""
        now = time.monotonic()
        live = prep["live"]
        expired = [r for r in live
                   if r.deadline is not None and now >= r.deadline]
        if not expired and all(not r.origin.future.done() for r in live):
            return prep
        for r in expired:
            self._fail(r, DeadlineExceededError(
                "request expired while staged for the next flush"),
                timeout=True)
        rest = [r for r in live if r not in expired]
        if not rest:
            return None
        return self._prepare_batch(rest, prep["reason"], staged=True,
                                   requeued=True)

    def _stage_next(self):
        """Pop + prepare the NEXT flush while the current one executes —
        called between forward dispatch and drain, so the prep's
        concat/pad/device placement hides under in-flight compute. Only a
        FULL flush already queued is staged: a partial queue keeps its
        ``max_wait`` coalescing window (identical batch shaping to
        lockstep), and an empty one has nothing to hide."""
        try:
            if self._admission._rows < self._max_batch:
                return None
            batch, reason = self._admission.get_batch_nowait(self._max_batch)
            if batch is None:
                return None
            if telemetry._enabled:
                telemetry.counter("serving.staged_flushes").inc()
            prep = self._prepare_batch(batch, reason, staged=True)
            if prep is None:
                return None
            return prep
        except Exception as e:  # noqa: BLE001 — never fail the IN-FLIGHT
            # batch because the NEXT one failed to stage; its requests die
            # here, already popped and unrunnable by anyone else
            self._logger.error("serving stage-ahead failed: %r", e)
            return None

    def _execute_prep(self, prep, stage=None):
        """Dispatch, (overlap) stage the next flush, drain, deliver.
        Returns the prep staged mid-flight, or None."""
        tele = telemetry._enabled
        trc = tracing._enabled
        live, reason = prep["live"], prep["reason"]
        rows, bucket = prep["rows"], prep["bucket"]
        feeds, earliest = prep["feeds"], prep["earliest"]
        # staged preps overlapped their prepare; their wall starts at
        # dispatch. Lockstep walls include the prepare they paid inline.
        t_wall0 = time.perf_counter() if prep["staged"] else prep["t0"]
        staged_box = [None]
        # the dispatch/drain split honors the `_run` seam: an instance
        # with `_run` patched over (test gates, wrappers) keeps the
        # lockstep call so the patch still sees every forward
        stage_fn = self._stage_next if (
            stage and _staging.overlap_enabled()
            and "_run" not in self._predictor.__dict__) else None
        state = {"first": stage_fn is not None}
        with tracing.span("serving.batch", cat="serving", rows=rows,
                          bucket=bucket, reason=reason,
                          staged=prep["staged"]):

            def attempt():
                # a retry must never run past the batch's earliest
                # deadline — DeadlineExceededError is not in retry_on, so
                # raising it here ends the retry loop immediately
                if earliest is not None and time.monotonic() >= earliest:
                    raise DeadlineExceededError(
                        "deadline passed before a (re)try could run")
                if state["first"]:
                    # overlap lane: host work (staging the next flush)
                    # between dispatch and drain, not before dispatch
                    state["first"] = False
                    pending = self._predictor._run_dispatch(bucket, feeds)
                    staged_box[0] = stage_fn()
                    return self._predictor._run_wait(pending)
                return self._predictor._run(bucket, feeds)

            t_exec0 = tracing.now_us() if trc else 0.0
            try:
                outs = retry_call(attempt,
                                  desc=f"serving forward bucket={bucket}",
                                  retries=self._retries,
                                  backoff=self._backoff_s,
                                  retry_on=self._predictor.retry_on)
            except DeadlineExceededError as e:
                now = time.monotonic()
                expired, rest = [], []
                for r in live:
                    (expired if r.deadline is not None and now >= r.deadline
                     else rest).append(r)
                for r in expired:
                    self._fail(r, e, timeout=True)
                if rest:
                    # survivors still have deadline budget: re-run without
                    # the expired requests (their rows no longer pad the
                    # batch)
                    self._run_batch(rest, reason)
                return staged_box[0]
            except Exception as e:  # noqa: BLE001 — fail batch, keep serving
                for r in live:
                    self._fail(r, e)
                return staged_box[0]
            if trc:
                # each request's view of the shared compute window: one
                # execute child per request makes every request tree
                # complete (admission -> queue -> execute -> reassembly)
                # without cross-referencing the batch span
                t_exec1 = tracing.now_us()
                for r in live:
                    sp = r.origin.span
                    if sp is not None:
                        tracing.emit_span("serving.execute", t_exec0,
                                          t_exec1 - t_exec0, cat="serving",
                                          parent=sp, bucket=bucket,
                                          batch_rows=rows)
            if tele:
                telemetry.counter("serving.batches").inc()
                telemetry.counter("serving.batch_rows").inc(rows)
                telemetry.counter("serving.batch_slots").inc(bucket)
                telemetry.counter(f"serving.flush_{reason}").inc()
                telemetry.histogram("serving.batch_occupancy").record(rows)
            off = 0
            done_ts = time.monotonic()
            for r in live:
                sliced = [o[off:off + r.rows] for o in outs]
                off += r.rows
                self._deliver(r, sliced, done_ts)
            if observatory._enabled:
                # the flush WALL (prep + dispatch + drain + deliver, minus
                # whatever staging hid); the predictor observed exec_s —
                # their gap is the serving lane's host_gap_us
                observatory.observe(
                    "serving", wall_s=time.perf_counter() - t_wall0)
        return staged_box[0]
