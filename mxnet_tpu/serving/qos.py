"""Multi-tenant QoS: priority classes, quotas and preemption policy.

Every queue in the serving subsystem is oldest-first by default, which is
the right policy for exactly one tenant. The moment a fleet serves many,
one bulk tenant's backlog starves every interactive tenant's TTFT — the
classic multi-tenancy failure. This module is the policy layer both
serving stacks consult:

* **tenant registry** — ``MXNET_QOS_SPEC`` declares tenants as
  ``name:class[:rps=N,tps=N,weight=N]`` entries (``;``-separated), with
  ``class`` one of ``interactive`` / ``standard`` / ``batch``. Unknown
  (or anonymous) tenants land in ``MXNET_QOS_DEFAULT_CLASS``. The spec
  is read ONCE, at the first :func:`active` call — construct servers
  after setting it (or use :func:`install` programmatically).
* **priority-classed, deadline-aware admission** — with a registry
  active, :class:`~.admission.AdmissionQueue` orders pops by
  ``(class rank, earliest deadline, enqueue time)`` instead of FIFO,
  with anti-starvation aging: a batch request waiting longer than
  ``MXNET_QOS_AGING_S`` is promoted to standard rank so a continuous
  interactive trickle cannot starve it forever.
* **quotas** — per-tenant request-rate (``rps``) and token-rate
  (``tps``) token buckets. An over-quota submit fails synchronously
  with :class:`QuotaExceededError` — fast, like ``QueueFullError``;
  backpressure is a signal, not a stall. Token spend is charged as
  tokens are DELIVERED (:meth:`TenantRegistry.charge_tokens`), so a
  tenant over its token budget is blocked from admitting new sessions
  until the bucket refills.
* **preemption policy** — ``weight`` (default by class: interactive
  2.0, standard 1.0, batch 0.25) feeds the fairness-weighted autoscale
  demand (``health.desired_engines``), and the class ranks drive the
  generation engine's park/preempt/resume decisions
  (``MXNET_QOS_PARK_SLOTS`` reserved KV-slab rows; see the engine).
* **per-tenant SLO rows** — :func:`attach_slo` appends one
  ``qos.ttft_us|tenant=<name>:p99<target>`` objective per declared
  tenant to the PR 11 burn tracker (class-default targets), so a single
  tenant's latency breach shows up as ITS burn rate, not an average.

Everything here is default-off: with no spec and no :func:`install`,
:func:`active` returns None and every consulting call site takes its
pre-QoS path unchanged (behavior AND compile accounting bit-identical —
pinned by ``test_qos.py``).
"""
from __future__ import annotations

import collections
import time

from .. import analysis
from .. import telemetry
from ..base import MXNetError, getenv, register_env
from .admission import ServingError

__all__ = ["QuotaExceededError", "TenantSpec", "TenantRegistry", "CLASSES",
           "BATCH_RANK", "parse_spec", "active", "install", "clear",
           "labeled_metric", "attach_slo"]

register_env("MXNET_QOS_SPEC", "",
             "multi-tenant QoS spec: ';'-separated "
             "'name:class[:rps=N,tps=N,weight=N]' entries (class one of "
             "interactive|standard|batch); empty = QoS layer off "
             "(FIFO admission, no quotas, no preemption)")
register_env("MXNET_QOS_DEFAULT_CLASS", "standard",
             "priority class for tenants the MXNET_QOS_SPEC does not "
             "declare (and for untenanted requests)")
register_env("MXNET_QOS_PARK_SLOTS", 1,
             "KV-slab slots each generation engine reserves as the "
             "preemption park region when QoS is active (0 disables "
             "preemption; ignored — and no slots reserved — while QoS "
             "is off)")
register_env("MXNET_QOS_AGING_S", 30.0,
             "anti-starvation aging: a batch-class request queued longer "
             "than this many seconds is promoted to standard rank "
             "(0 disables aging)")

CLASSES = ("interactive", "standard", "batch")
_RANK = {"interactive": 0, "standard": 1, "batch": 2}
BATCH_RANK = _RANK["batch"]
# class-default fairness weights (autoscale demand) and TTFT p99 SLO
# targets (attach_slo) — an explicit per-tenant weight overrides
_CLASS_WEIGHT = {"interactive": 2.0, "standard": 1.0, "batch": 0.25}
_CLASS_TTFT_MS = {"interactive": 500.0, "standard": 2000.0,
                  "batch": 10000.0}


class QuotaExceededError(ServingError):
    """The tenant is over its request-rate (or token-rate) quota. Raised
    synchronously from ``submit()`` — the cheap per-tenant analog of
    ``QueueFullError``: shed or defer THIS tenant's load now instead of
    letting it crowd the shared queue."""


class TenantSpec:
    """One tenant's QoS contract: priority class, quotas, weight."""

    __slots__ = ("name", "cls", "rank", "rps", "tps", "weight")

    def __init__(self, name, cls, rps=None, tps=None, weight=None):
        if cls not in _RANK:
            raise MXNetError(
                f"QoS class {cls!r} for tenant {name!r} not one of "
                f"{'|'.join(CLASSES)}")
        for label, v in (("rps", rps), ("tps", tps), ("weight", weight)):
            if v is not None and not v > 0:
                raise MXNetError(
                    f"QoS {label} for tenant {name!r} must be > 0, "
                    f"got {v!r}")
        self.name = name
        self.cls = cls
        self.rank = _RANK[cls]
        self.rps = None if rps is None else float(rps)
        self.tps = None if tps is None else float(tps)
        self.weight = (_CLASS_WEIGHT[cls] if weight is None
                       else float(weight))


def parse_spec(text):
    """Parse an ``MXNET_QOS_SPEC`` string into ``{name: TenantSpec}``."""
    tenants = {}
    for entry in (text or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3) or not parts[0].strip():
            raise MXNetError(
                f"MXNET_QOS_SPEC entry {entry!r}: expected "
                "'name:class[:rps=N,tps=N,weight=N]'")
        name, cls = parts[0].strip(), parts[1].strip()
        kv = {}
        if len(parts) == 3:
            for tok in parts[2].split(","):
                tok = tok.strip()
                if not tok:
                    continue
                k, eq, v = tok.partition("=")
                k = k.strip()
                if not eq or k not in ("rps", "tps", "weight"):
                    raise MXNetError(
                        f"MXNET_QOS_SPEC entry {entry!r}: bad option "
                        f"{tok!r} (rps=/tps=/weight=)")
                try:
                    kv[k] = float(v)
                except ValueError:
                    raise MXNetError(
                        f"MXNET_QOS_SPEC entry {entry!r}: {k} value "
                        f"{v!r} is not a number")
        if name in tenants:
            raise MXNetError(
                f"MXNET_QOS_SPEC declares tenant {name!r} twice")
        tenants[name] = TenantSpec(name, cls, **kv)
    return tenants


class TenantRegistry:
    """The active tenant set plus its quota state.

    Quotas are classic token buckets (capacity = one second of rate,
    refilled continuously): :meth:`check_admit` spends one request
    token and verifies the token-rate bucket is not exhausted;
    :meth:`charge_tokens` debits delivered generation tokens — the
    bucket may go negative, which blocks new admissions until the
    refill catches up. Unknown tenant names get a quota-free
    default-class spec (cached per name — label cardinality is the
    operator's contract, see docs/faq/perf.md)."""

    def __init__(self, tenants=None, default_class=None, aging_s=None):
        self.tenants = dict(tenants or {})
        self.default_class = (getenv("MXNET_QOS_DEFAULT_CLASS")
                              if default_class is None else default_class)
        if self.default_class not in _RANK:
            raise MXNetError(
                f"MXNET_QOS_DEFAULT_CLASS {self.default_class!r} not one "
                f"of {'|'.join(CLASSES)}")
        self.aging_s = float(getenv("MXNET_QOS_AGING_S")
                             if aging_s is None else aging_s)
        self.default_rank = _RANK[self.default_class]
        self._defaults = {}          # unknown tenant name -> cached spec
        self._lock = analysis.make_lock("qos.registry")
        # token buckets, keyed by declared-tenant name: level + last
        # refill instant. Requests start at full capacity so the first
        # second of traffic is never throttled by an empty bucket.
        self._req = {}
        self._tok = {}
        now = time.monotonic()
        for name, spec in self.tenants.items():
            if spec.rps is not None:
                self._req[name] = [max(spec.rps, 1.0), now]
            if spec.tps is not None:
                self._tok[name] = [max(spec.tps, 1.0), now]

    def spec_for(self, tenant):
        """The tenant's :class:`TenantSpec` (a cached default-class spec
        for unknown names; ``None`` maps to the name ``"default"``)."""
        name = "default" if tenant is None else str(tenant)
        spec = self.tenants.get(name)
        if spec is not None:
            return spec
        spec = self._defaults.get(name)
        if spec is None:
            spec = self._defaults[name] = TenantSpec(
                name, self.default_class)
        return spec

    def rank(self, tenant):
        return self.spec_for(tenant).rank

    def weight(self, tenant):
        return self.spec_for(tenant).weight

    def effective_rank(self, rank, enqueued_at, now):
        """The rank admission ordering uses: batch promoted to standard
        once queued past the aging window (anti-starvation)."""
        if rank is None:
            rank = self.default_rank
        if (rank >= BATCH_RANK and self.aging_s > 0
                and now - enqueued_at >= self.aging_s):
            return _RANK["standard"]
        return rank

    @staticmethod
    def _refill(bucket, rate, now):
        level, t0 = bucket
        level = min(level + (now - t0) * rate, max(rate, 1.0))
        bucket[0] = level
        bucket[1] = now
        return level

    def check_admit(self, tenant, now=None):
        """Spend one request-rate token; raise :class:`QuotaExceededError`
        when the tenant is over either quota. No-op for quota-free
        tenants."""
        spec = self.spec_for(tenant)
        if spec.rps is None and spec.tps is None:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            if spec.tps is not None:
                level = self._refill(self._tok[spec.name], spec.tps, now)
                if level <= 0:
                    raise QuotaExceededError(
                        f"tenant {spec.name!r} over its token-rate quota "
                        f"({spec.tps:g} tok/s): retry after the bucket "
                        "refills")
            if spec.rps is not None:
                bucket = self._req[spec.name]
                level = self._refill(bucket, spec.rps, now)
                if level < 1.0:
                    raise QuotaExceededError(
                        f"tenant {spec.name!r} over its request-rate "
                        f"quota ({spec.rps:g} req/s): shed or defer this "
                        "tenant's load")
                bucket[0] = level - 1.0

    def charge_tokens(self, tenant, n, now=None):
        """Debit ``n`` delivered tokens against the tenant's token-rate
        bucket (may go negative — new admissions block until refill)."""
        spec = self.spec_for(tenant)
        if spec.tps is None:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            bucket = self._tok[spec.name]
            self._refill(bucket, spec.tps, now)
            bucket[0] -= n

    def slo_specs(self):
        """One TTFT p99 objective spec per DECLARED tenant (class-default
        targets) — what :func:`attach_slo` feeds the burn tracker."""
        return [
            f"qos.ttft_us|tenant={spec.name}:"
            f"p99<{_CLASS_TTFT_MS[spec.cls]:g}ms"
            for _, spec in sorted(self.tenants.items())]


def labeled_metric(name, spec):
    """The tenant/class-labeled telemetry name for ``spec`` — rendered
    by ``prom_text`` as ``mxnet_<name>{tenant="...",class="..."}``."""
    return telemetry.labeled(name, tenant=spec.name,
                             **{"class": spec.cls})


# ---------------------------------------------------------------------------
# Active-registry lifecycle
# ---------------------------------------------------------------------------

_lock = analysis.make_lock("qos.active")
_registry = None
_resolved = False


def active():
    """The process's active :class:`TenantRegistry`, or None when QoS is
    off. Resolved once from ``MXNET_QOS_SPEC`` (empty = off) unless
    :func:`install` overrode it; queues and engines capture the result
    at construction, so set the spec (or install) BEFORE building
    servers."""
    global _registry, _resolved
    if _resolved:
        return _registry
    with _lock:
        if not _resolved:
            spec = getenv("MXNET_QOS_SPEC")
            _registry = TenantRegistry(parse_spec(spec)) if spec else None
            _resolved = True
    return _registry


def install(registry):
    """Activate ``registry`` programmatically (tests / bench), overriding
    ``MXNET_QOS_SPEC`` until :func:`clear`. Returns the registry."""
    global _registry, _resolved
    with _lock:
        _registry = registry
        _resolved = True
    return registry


def clear():
    """Forget the active registry; the next :func:`active` re-reads
    ``MXNET_QOS_SPEC``."""
    global _registry, _resolved
    with _lock:
        _registry = None
        _resolved = False


def attach_slo(registry=None, tracker=None):
    """Append one per-tenant TTFT burn objective per declared tenant to
    the health SLO tracker (idempotent; no-op while QoS or the health
    layer is off). Returns the number of objectives added."""
    from .. import health

    registry = active() if registry is None else registry
    if registry is None or not health._enabled:
        return 0
    tracker = health.tracker() if tracker is None else tracker
    if tracker is None:
        return 0
    added = 0
    with tracker._lock:
        have = {o.spec for o in tracker.objectives}
        for spec in registry.slo_specs():
            if spec in have:
                continue
            obj = health.Objective(spec)
            tracker.objectives.append(obj)
            tracker._samples.setdefault(obj.key, collections.deque())
            added += 1
    return added
