"""Speculative decoding drafts — propose k tokens per tick, let the slab
verify them.

Plain continuous batching advances every session ONE token per fused tick;
the engine's speculative lane advances up to ``k + 1``: a cheap *draft*
proposes k tokens per live slot, the target model checks all of them in
ONE fixed-shape verify executable (:meth:`TransformerLM.verify_step` — k+1
unrolled decode graphs, so greedy output stays BIT-EXACT with the plain
path), and the engine commits the longest agreeing prefix plus the
target's own next token. The draft never affects WHAT is generated — only
how many verify positions pay off — so any draft is safe; a good one
turns the acceptance ratio into tokens-per-tick.

Two drafts:

* :class:`NgramDraft` (the default) — host-side prompt-lookup: propose
  the continuation of the most recent earlier occurrence of the session's
  own trailing n-gram. Zero device state, zero compiles, surprisingly
  effective on templated/repetitive output (the self-speculation trick).
* :class:`CheckpointDraft` — a small :class:`TransformerLM` loaded from
  ``MXNET_GENERATION_DRAFT`` (:func:`save_draft` / :func:`load_draft`
  ``.npz`` checkpoints). It keeps its OWN fixed-shape KV slab mirroring
  the engine's slots and obeys the same compile-once discipline: one
  prefill entry per bucket (admission) plus ONE fused ``draft_step``
  that ingests the tick's committed tokens (variable count per slot,
  handled by write-masking-free frontier sequencing — invalid rows are
  overwritten before they could ever be attended) and then rolls k
  greedy proposal steps, all in a single executable with the draft slab
  donated.

The engine calls drafts through a small lifecycle protocol
(:class:`Draft`): ``attach`` (engine shape/cache wiring), ``warm``
(compile-ahead, counted), ``on_admit``/``on_evict``/``on_commit``
(per-slot state), ``reset`` (slab reallocation after a failed tick) and
``propose`` (the per-tick [S, k] block).
"""
from __future__ import annotations

import json

import numpy as np

from ... import memory
from ...base import MXNetError, getenv, register_env

__all__ = ["Draft", "NgramDraft", "CheckpointDraft", "save_draft",
           "load_draft", "default_draft"]

register_env("MXNET_GENERATION_SPEC_K", 0,
             "speculative decoding draft length k (tokens proposed per "
             "tick; the verify advances each slot by up to k+1). 0 "
             "disables the speculative lane (plain one-token decode)")
register_env("MXNET_GENERATION_DRAFT", "",
             "path to a save_draft() .npz checkpoint for the speculative "
             "draft model; empty = the host-side n-gram (prompt-lookup) "
             "fallback draft")

# bound the n-gram scan window: proposals are free to be wrong (the verify
# corrects), so an O(history) scan per slot per tick buys nothing past the
# recent context
_NGRAM_WINDOW = 256


class Draft:
    """Draft lifecycle protocol (no-op defaults; subclass what you need)."""

    def attach(self, engine):
        """Called once from the engine constructor with the owning engine
        (slots, max_len, buckets, compile cache, model mesh)."""

    def warm(self):
        """Compile-ahead every draft executable; return the number of
        entries this draft pins (0 for host-side drafts)."""
        return 0

    def on_admit(self, slot, prompt, first_tok):
        """A session entered ``slot`` with ``prompt`` and its prefill
        produced ``first_tok`` (committed but not yet fed anywhere)."""

    def on_commit(self, slot, committed):
        """The verify tick committed ``committed`` (list of ints, length
        1..k+1) for ``slot``."""

    def on_evict(self, slot):
        """The session in ``slot`` ended."""

    def reset(self):
        """The engine reallocated its slab after a failed tick; drop any
        per-slot device state the same way."""

    def swap_params(self, params):
        """A weight rollout published new draft parameters; flip to them
        (host dict, same names/shapes). Returns True when the draft has
        parameters to swap (False for host-side drafts — a no-op)."""
        return False

    def propose(self, k, sessions):
        """Return an int32 [S, k] proposal block (rows of dead slots are
        ignored). ``sessions`` is the engine's slot list (None = dead);
        each live session exposes ``.prompt`` and ``.stream.tokens``."""
        raise NotImplementedError


class NgramDraft(Draft):
    """Prompt-lookup draft: continue the most recent earlier occurrence
    of the session's trailing n-gram (n = 3, 2, 1), falling back to
    repeating the last token. Pure host work on metadata the engine
    already keeps — the zero-dependency default draft."""

    def __init__(self, max_ngram=3):
        self._n = int(max_ngram)

    def _propose_one(self, hist, k):
        n = hist.size
        lo = max(n - _NGRAM_WINDOW, 0)
        for g in range(min(self._n, n - 1), 0, -1):
            pat = hist[n - g:]
            # most recent earlier occurrence with at least one
            # continuation token
            for i in range(n - g - 1, lo - 1, -1):
                if np.array_equal(hist[i:i + g], pat):
                    cont = hist[i + g:i + g + k]
                    if cont.size:
                        out = list(cont)
                        while len(out) < k:
                            out.append(out[-1])
                        return out
        return [int(hist[-1])] * k

    def propose(self, k, sessions):
        out = np.zeros((len(sessions), k), np.int32)
        for s, sess in enumerate(sessions):
            if sess is None:
                continue
            hist = np.concatenate(
                [sess.prompt, np.asarray(sess.stream.tokens, np.int32)])
            out[s] = self._propose_one(hist, k)
        return out


class CheckpointDraft(Draft):
    """A small TransformerLM draft with its own fixed-shape KV slab.

    Per engine tick it runs ONE fused ``draft_step``: unrolled ingest of
    the tick's committed block (per-slot valid counts — invalid trailing
    rows land beyond the slot's frontier and are overwritten by the next
    ingest before anything attends them) followed by k unrolled greedy
    proposal steps whose K/V writes are speculative in the same
    frontier-safe way. The draft slab therefore needs ``max_len + 2k``
    rows of positional headroom; :meth:`attach` raises when the draft
    model's ``max_len`` cannot cover it.
    """

    def __init__(self, model, params):
        self._model = model
        self._params = params
        self._eng = None
        self._dk = self._dv = None
        self._len = None          # per-slot draft frontier
        self._pending = None      # per-slot committed-not-ingested tokens

    def attach(self, engine):
        self._eng = engine
        k = engine.spec_k
        need = engine.max_len + 2 * k
        if need > self._model.cfg.max_len:
            raise MXNetError(
                f"draft model positional range {self._model.cfg.max_len} < "
                f"engine max_len {engine.max_len} + 2*k ({need} rows needed "
                "for speculative scratch); lower MXNET_GENERATION_MAX_LEN / "
                "MXNET_GENERATION_SPEC_K or train a longer draft")
        self._slab_len = need
        self._alloc()
        # total_slots (not max_slots): the draft slab mirrors the engine's
        # slab row-for-row, INCLUDING the QoS park region — a preempted
        # session's resume re-prefills the draft row anyway, but every
        # propose/ingest runs fixed-shape over the whole slab, so the
        # shapes (and executable keys) must match. QoS off: identical.
        self._len = np.zeros(engine.total_slots, np.int32)
        self._pending = [[] for _ in range(engine.total_slots)]
        # the draft slab is replaced by every donated draft_step — a live
        # view, like the engine's own slab; distinct buffers, so the
        # census adds it to kv_cache without double-counting the target's
        memory.register_provider("kv_cache", self,
                                 lambda d: [d._dk, d._dv])

    def _alloc(self):
        self._dk, self._dv = self._model.init_cache(
            self._eng.total_slots, self._slab_len)

    def slab_bytes(self):
        return int(self._dk.nbytes) + int(self._dv.nbytes)

    # -- compiled programs ---------------------------------------------------

    def _prefill_fn(self, bucket):
        model, cache = self._model, self._eng.cache

        def build():
            import jax

            def fn(params, dk, dv, toks, length, slot):
                _, dk, dv = model.prefill(params, dk, dv, toks, length, slot)
                return dk, dv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("draft_prefill", bucket, self._eng.total_slots,
               self._slab_len)
        # audit="generation": the draft slab programs live in the engine's
        # "generation" cache (passed in) — same hlolint contract row
        return cache.get_or_build(key, build, persistent=False,
                                  audit="generation")

    def _step_fn(self, k):
        model, cache = self._model, self._eng.cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, dk, dv, tokens, counts, positions):
                # phase 1: ingest the committed block (k+1 unrolled decode
                # graphs); stash each step's greedy argmax
                nxt = []
                for i in range(k + 1):
                    lg, dk, dv = model.decode_step(params, dk, dv,
                                                   tokens[:, i],
                                                   positions + i)
                    nxt.append(jnp.argmax(lg, axis=-1).astype(jnp.int32))
                nxt = jnp.stack(nxt, axis=1)                     # [S, k+1]
                # the first proposal continues the LAST VALID ingested
                # token (index counts-1; dead slots clamp to 0 — garbage
                # the engine discards)
                idx = jnp.maximum(counts - 1, 0)[:, None]
                cur = jnp.take_along_axis(nxt, idx, axis=1)[:, 0]
                props = [cur]
                # phase 2: k-1 more greedy steps feeding our own
                # proposals; their writes start at the post-ingest
                # frontier (positions + counts) and are speculative
                for j in range(k - 1):
                    lg, dk, dv = model.decode_step(params, dk, dv, cur,
                                                   positions + counts + j)
                    cur = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    props.append(cur)
                return jnp.stack(props, axis=1), dk, dv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("draft_step", k, self._eng.total_slots, self._slab_len)
        return cache.get_or_build(key, build, persistent=False,
                                  audit="generation")

    # -- lifecycle -----------------------------------------------------------

    def warm(self):
        import jax.numpy as jnp

        eng = self._eng
        misses0 = eng.cache.misses
        for b in eng.prefill_buckets:
            fn = self._prefill_fn(b)
            self._dk, self._dv = fn(
                self._params, self._dk, self._dv,
                jnp.zeros((b,), jnp.int32), jnp.asarray(1, jnp.int32),
                jnp.asarray(0, jnp.int32))
        k = eng.spec_k
        fn = self._step_fn(k)
        _, self._dk, self._dv = fn(
            self._params, self._dk, self._dv,
            jnp.zeros((eng.total_slots, k + 1), jnp.int32),
            jnp.zeros(eng.total_slots, jnp.int32),
            jnp.zeros(eng.total_slots, jnp.int32))
        # warm garbage lands in rows the next real prefill/ingest
        # overwrites before attending (the frontier argument); lengths
        # were never advanced, so no state to undo
        return eng.cache.misses - misses0

    def on_admit(self, slot, prompt, first_tok):
        import jax.numpy as jnp

        eng = self._eng
        n = int(prompt.size)
        bucket = eng.bucket_for(n)
        padded = np.zeros(bucket, np.int32)
        padded[:n] = prompt
        fn = self._prefill_fn(bucket)
        self._dk, self._dv = fn(
            self._params, self._dk, self._dv, jnp.asarray(padded),
            jnp.asarray(n, jnp.int32), jnp.asarray(slot, jnp.int32))
        self._len[slot] = n
        self._pending[slot] = [int(first_tok)]

    def on_commit(self, slot, committed):
        self._pending[slot] = [int(t) for t in committed]

    def on_evict(self, slot):
        self._len[slot] = 0
        self._pending[slot] = []

    def reset(self):
        self._alloc()
        self._len[:] = 0
        self._pending = [[] for _ in range(self._eng.total_slots)]

    def swap_params(self, params):
        """Flip the draft to new weights immediately — the slab survives
        untouched. Rows ingested under the old weights only degrade the
        acceptance ratio until overwritten (the target's verify is the
        ground truth, so output never changes); shapes/dtypes must match
        so the pinned draft executables are reused compile-free."""
        import jax

        cur = self._params
        new = {str(k): v for k, v in dict(params).items()}
        if set(new) != set(cur):
            raise MXNetError(
                f"draft swap_params: parameter names differ (have "
                f"{sorted(cur)}, got {sorted(new)})")
        specs = self._model.param_specs()
        placed = {}
        for name, v in new.items():
            arr = np.asarray(v)
            old = cur[name]
            if tuple(arr.shape) != tuple(old.shape):
                raise MXNetError(
                    f"draft swap_params: {name!r} shape "
                    f"{tuple(arr.shape)} != bound {tuple(old.shape)}")
            placed[name] = jax.device_put(
                arr.astype(old.dtype, copy=False), specs[name])
        self._params = placed
        return True

    def propose(self, k, sessions):
        import jax.numpy as jnp

        S = len(sessions)
        tokens = np.zeros((S, k + 1), np.int32)
        counts = np.zeros(S, np.int32)
        for s, sess in enumerate(sessions):
            if sess is None:
                continue
            pend = self._pending[s]
            tokens[s, :len(pend)] = pend
            counts[s] = len(pend)
        fn = self._step_fn(k)
        props, self._dk, self._dv = fn(
            self._params, self._dk, self._dv, jnp.asarray(tokens),
            jnp.asarray(counts), jnp.asarray(self._len))
        self._len += counts
        for s, sess in enumerate(sessions):
            if sess is not None:
                self._pending[s] = []
        return np.asarray(props)


# ---------------------------------------------------------------------------
# Checkpoint format
# ---------------------------------------------------------------------------


def save_draft(path, model, params):
    """Persist a TransformerLM draft as one ``.npz``: the config as an
    embedded JSON field plus every parameter array (dict keys survive as
    npz member names)."""
    import dataclasses

    arrays = {name: np.asarray(v) for name, v in params.items()}
    np.savez(path, __config__=json.dumps(dataclasses.asdict(model.cfg)),
             **arrays)


def load_draft(path, mesh=None):
    """Load a :func:`save_draft` checkpoint: returns ``(model, params)``
    with every parameter placed per the model's partition specs."""
    import jax

    from ...models import TransformerLM, TransformerLMConfig

    with np.load(path, allow_pickle=False) as z:
        cfg = TransformerLMConfig(**json.loads(str(z["__config__"])))
        model = TransformerLM(cfg, mesh)
        specs = model.param_specs()
        params = {name: jax.device_put(z[name], specs[name])
                  for name in z.files if name != "__config__"}
    return model, params


def default_draft(mesh=None):
    """The draft the engine uses when none is passed: a
    :class:`CheckpointDraft` from ``MXNET_GENERATION_DRAFT`` when set,
    else the :class:`NgramDraft` fallback."""
    path = str(getenv("MXNET_GENERATION_DRAFT")).strip()
    if path:
        model, params = load_draft(path, mesh)
        return CheckpointDraft(model, params)
    return NgramDraft()
