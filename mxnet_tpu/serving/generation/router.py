"""GenerationRouter — spread sessions across engine replicas by occupancy.

One :class:`~mxnet_tpu.serving.generation.engine.GenerationEngine` is one
model replica with one KV slab; scale-out is N of them behind this router.
Placement is LOAD-AWARE, not round-robin: each submit goes to the replica
with the lowest ``(live slots + queued sessions) / max_slots`` — queued
sessions count so that a burst doesn't pile onto one replica before its
prefills land — with a rotating tie-break so equal-load replicas (an idle
fleet) still share evenly. A replica rejecting with ``QueueFullError``
fails over to the next-least-loaded one; only when EVERY replica is full
does the caller see backpressure.
"""
from __future__ import annotations

import itertools

from ... import telemetry
from ...base import MXNetError
from ..admission import QueueFullError

__all__ = ["GenerationRouter"]


class GenerationRouter:
    """Occupancy-balancing front end over N generation engines."""

    def __init__(self, engines):
        engines = list(engines)
        if not engines:
            raise MXNetError("GenerationRouter needs >= 1 engine")
        self._engines = engines
        self._rr = itertools.count()

    @property
    def engines(self):
        return list(self._engines)

    def loads(self):
        """Per-replica occupancy, the placement signal."""
        return [e.load for e in self._engines]

    def submit(self, prompt, **kwargs):
        """Place one session on the least-loaded replica (rotating
        tie-break); fail over across replicas on ``QueueFullError`` and
        re-raise it only when every replica is saturated."""
        n = len(self._engines)
        k = next(self._rr)
        order = sorted(range(n),
                       key=lambda i: (self._engines[(i + k) % n].load, i))
        last_exc = None
        for i in order:
            eng = self._engines[(i + k) % n]
            try:
                stream = eng.submit(prompt, **kwargs)
            except QueueFullError as e:
                last_exc = e
                continue
            if telemetry._enabled:
                telemetry.counter("serving.generation.routed").inc()
            return stream
        raise last_exc if last_exc is not None else QueueFullError(
            "every generation replica is saturated")

    def generate(self, prompt, **kwargs):
        """Blocking convenience: route, then collect the full token list."""
        return list(self.submit(prompt, **kwargs))

    def warm(self, buckets=None):
        """Warm every replica (each compiles its own executables); sums
        the compile counts — ``serving.warmup`` reports through this."""
        out = {"buckets": None, "compiles": 0, "seconds": 0.0,
               "cache_entries": 0}
        for e in self._engines:
            w = e.warm(buckets)
            out["buckets"] = w["buckets"]
            out["compiles"] += w["compiles"]
            out["seconds"] += w["seconds"]
            out["cache_entries"] += w["cache_entries"]
        return out

    def close(self, timeout=None):
        for e in self._engines:
            e.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self):
        return {"replicas": len(self._engines),
                "loads": self.loads(),
                "engines": [e.stats() for e in self._engines]}
