"""GenerationRouter — spread sessions across engine replicas by occupancy.

One :class:`~mxnet_tpu.serving.generation.engine.GenerationEngine` is one
model replica with one KV slab; scale-out is N of them behind this router.
Placement is LOAD-AWARE, not round-robin: each submit goes to the replica
with the lowest ``(live slots + queued sessions) / max_slots`` — queued
sessions count so that a burst doesn't pile onto one replica before its
prefills land — with a rotating tie-break so equal-load replicas (an idle
fleet) still share evenly. A replica rejecting with ``QueueFullError``
fails over to the next-least-loaded one; only when EVERY replica is full
does the caller see backpressure.

Under ``MXNET_HEALTH=1`` placement also consults per-engine READINESS
(:meth:`GenerationEngine.ready`): an unready replica — wedged scheduler
(watchdog-stalled beacon), intake queue above the watermark, draining
after ``close()`` — is **drained**: the router stops placing new
sessions there while its live sessions finish, and re-admits it the
moment the probe passes again. Transitions land in the health event
journal (``engine_drain`` / ``engine_undrain``) and the
``health.ready_engines`` gauge. A fleet with NO ready replica falls back
to load-order over all of them (availability over strictness — the
engines' own backpressure still bounds the damage). The router also
registers itself as an autoscale source
(:func:`mxnet_tpu.health.register_fleet`), feeding the
``health.desired_engines`` gauge.
"""
from __future__ import annotations

import itertools

from ... import health
from ... import telemetry
from ...base import MXNetError
from ..admission import QueueFullError

__all__ = ["GenerationRouter"]


class GenerationRouter:
    """Occupancy-balancing front end over N generation engines."""

    def __init__(self, engines):
        engines = list(engines)
        if not engines:
            raise MXNetError("GenerationRouter needs >= 1 engine")
        self._engines = engines
        self._rr = itertools.count()
        self._ready_state = {}      # engine index -> last readiness bool
        self._all_unready = False
        health.register_fleet(self)

    @property
    def engines(self):
        return list(self._engines)

    def loads(self):
        """Per-replica occupancy, the placement signal."""
        return [e.load for e in self._engines]

    def _ready_indices(self):
        """Readiness sweep (health gate on): the engine indices placement
        may use, with drain/undrain transitions journaled. Falls back to
        ALL indices when nothing is ready."""
        ready = []
        for i, eng in enumerate(self._engines):
            ok, reason = eng.ready()
            prev = self._ready_state.get(i)
            # journal the transition — including a first sweep that finds
            # the engine already unready (a wedge that predates traffic)
            if prev != ok and not (prev is None and ok):
                kind = "engine_undrain" if ok else "engine_drain"
                health.event(kind, engine=eng.health_name, index=i,
                             reason=reason)
                telemetry.counter(
                    "health.undrains" if ok else "health.drains").inc()
            self._ready_state[i] = ok
            if ok:
                ready.append(i)
        telemetry.gauge("health.ready_engines").set(len(ready))
        if not ready:
            # availability over strictness: an all-unready fleet still
            # places by load (engines' own backpressure bounds the harm)
            if not self._all_unready:
                self._all_unready = True
                health.event("fleet_all_unready",
                             engines=len(self._engines))
            return list(range(len(self._engines)))
        self._all_unready = False
        return ready

    def submit(self, prompt, **kwargs):
        """Place one session on the least-loaded READY replica (rotating
        tie-break; every replica when health is off or none is ready);
        fail over across replicas on ``QueueFullError`` and re-raise it
        only when every candidate is saturated."""
        n = len(self._engines)
        k = next(self._rr)
        candidates = (set(self._ready_indices()) if health._enabled
                      else None)
        order = sorted(range(n),
                       key=lambda i: (self._engines[(i + k) % n].load, i))
        last_exc = None
        for i in order:
            if candidates is not None and (i + k) % n not in candidates:
                continue
            eng = self._engines[(i + k) % n]
            try:
                stream = eng.submit(prompt, **kwargs)
            except QueueFullError as e:
                last_exc = e
                continue
            if telemetry._enabled:
                telemetry.counter("serving.generation.routed").inc()
            return stream
        raise last_exc if last_exc is not None else QueueFullError(
            "every generation replica is saturated")

    def generate(self, prompt, **kwargs):
        """Blocking convenience: route, then collect the full token list."""
        return list(self.submit(prompt, **kwargs))

    def warm(self, buckets=None):
        """Warm every replica (each compiles its own executables); sums
        the compile counts — ``serving.warmup`` reports through this."""
        out = {"buckets": None, "compiles": 0, "seconds": 0.0,
               "cache_entries": 0}
        for e in self._engines:
            w = e.warm(buckets)
            out["buckets"] = w["buckets"]
            out["compiles"] += w["compiles"]
            out["seconds"] += w["seconds"]
            out["cache_entries"] += w["cache_entries"]
        return out

    def close(self, timeout=None):
        for e in self._engines:
            e.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self):
        return {"replicas": len(self._engines),
                "loads": self.loads(),
                "engines": [e.stats() for e in self._engines]}
