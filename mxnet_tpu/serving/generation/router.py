"""GenerationRouter — spread sessions across engine replicas by prefix
affinity and occupancy, and actuate the autoscale signal.

One :class:`~mxnet_tpu.serving.generation.engine.GenerationEngine` is one
model replica with one KV slab; scale-out is N of them behind this router.
Placement is decided in two tiers:

* **prefix affinity** — each replica's
  :meth:`~GenerationEngine.prefix_match_len` reports how many of the
  prompt's tokens its radix prefix cache could fork (a cheap host trie
  walk, no device work); the router places the session on the replica
  with the LONGEST usable match. Without this a fleet cold-misses a
  shared system prompt N-1 times: every replica would pay its own full
  prefill for a prefix some other replica already cached. Affinity
  placements are journaled (``router_affinity`` health events) and
  counted (``serving.generation.routed_affinity``).
* **load** — no usable match anywhere: the replica with the lowest
  ``(live slots + queued sessions) / max_slots`` wins (queued sessions
  count so a burst doesn't pile onto one replica before its prefills
  land), with a rotating tie-break so an idle fleet still shares evenly.

A replica rejecting with ``QueueFullError`` fails over to the next
candidate; only when EVERY replica is full does the caller see
backpressure.

Under ``MXNET_HEALTH=1`` placement also consults per-engine READINESS
(:meth:`GenerationEngine.ready`): an unready replica — wedged scheduler
(watchdog-stalled beacon), intake queue above the watermark, draining
after ``close()`` — is **drained**: the router stops placing new
sessions there while its live sessions finish, and re-admits it the
moment the probe passes again. Transitions land in the health event
journal (``engine_drain`` / ``engine_undrain``) and the
``health.ready_engines`` gauge. A fleet with NO ready replica falls back
to load-order over all of them (availability over strictness — the
engines' own backpressure still bounds the damage).

**Autoscale actuator** — the router registers as an autoscale source
(:func:`mxnet_tpu.health.register_fleet`, feeding the
``health.desired_engines`` gauge), and with an engine ``factory`` it can
also ACT on the signal: :meth:`scale_to` constructs (and warms) new
replicas or drains surplus ones (close in a background thread — live
sessions finish, zero drops), and :meth:`bind_autoscale` wires
:func:`mxnet_tpu.health.on_autoscale` straight to it, closing PR 11's
"signal with no actuator" gap for single-host fleets.
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref

from ... import analysis
from ... import health
from ... import telemetry
from ...base import MXNetError, getenv
from .. import qos
from ..admission import QueueFullError, ServerClosedError

__all__ = ["GenerationRouter"]


class GenerationRouter:
    """Affinity- and occupancy-balancing front end over N generation
    engines.

    Parameters
    ----------
    engines : list[GenerationEngine]
        The initial fleet (>= 1 replica).
    factory : callable, optional
        Zero-arg constructor for one new engine — required for
        :meth:`scale_to` growth / :meth:`bind_autoscale`.
    min_engines / max_engines : int, optional
        Clamp for :meth:`scale_to` (defaults: 1 / no upper bound).
    """

    def __init__(self, engines, factory=None, min_engines=1,
                 max_engines=None):
        engines = list(engines)
        if not engines:
            raise MXNetError("GenerationRouter needs >= 1 engine")
        self._engines = engines
        self._factory = factory
        self._min = max(int(min_engines), 1)
        self._max = None if max_engines is None else int(max_engines)
        self._rr = itertools.count()
        self._lock = analysis.make_lock("generation.router.engines")
        self._scale_lock = analysis.make_lock("generation.router.scale")
        self._ready_state = {}      # engine health_name -> last ready bool
        self._all_unready = False
        self._draining = []         # (engine, closer thread) during shrink
        self._closed = False
        # weight rollout: the fleet's current + previous WeightSets stay
        # pinned here so rolling_swap always has a rollback target (and
        # scale_to growth can bring a fresh replica onto the current
        # version — its factory closure captures construction params)
        self._ws_current = None
        self._ws_previous = None
        health.register_fleet(self)

    @property
    def engines(self):
        with self._lock:
            return list(self._engines)

    def loads(self):
        """Per-replica occupancy, the placement signal."""
        return [e.load for e in self.engines]

    def _ready_indices(self, engines):
        """Readiness sweep (health gate on): the engine indices placement
        may use, with drain/undrain transitions journaled. Falls back to
        ALL indices when nothing is ready."""
        ready = []
        for i, eng in enumerate(engines):
            ok, reason = eng.ready()
            key = eng.health_name
            prev = self._ready_state.get(key)
            # journal the transition — including a first sweep that finds
            # the engine already unready (a wedge that predates traffic)
            if prev != ok and not (prev is None and ok):
                kind = "engine_undrain" if ok else "engine_drain"
                health.event(kind, engine=key, index=i, reason=reason)
                telemetry.counter(
                    "health.undrains" if ok else "health.drains").inc()
            self._ready_state[key] = ok
            if ok:
                ready.append(i)
        # prune state for drained replicas — under autoscale churn every
        # grow cycle mints a fresh engine name, and an unpruned dict
        # grows for the life of the server
        live = {e.health_name for e in engines}
        for key in [k for k in self._ready_state if k not in live]:
            del self._ready_state[key]
        telemetry.gauge("health.ready_engines").set(len(ready))
        if not ready:
            # availability over strictness: an all-unready fleet still
            # places by load (engines' own backpressure bounds the harm)
            if not self._all_unready:
                self._all_unready = True
                health.event("fleet_all_unready", engines=len(engines))
            return list(range(len(engines)))
        self._all_unready = False
        return ready

    def submit(self, prompt, **kwargs):
        """Place one session: longest cached prompt prefix first, then
        least-loaded (rotating tie-break; READY replicas only when health
        is on and any is ready); fail over across replicas on
        ``QueueFullError`` and re-raise it only when every candidate is
        saturated."""
        engines = self.engines
        n = len(engines)
        k = next(self._rr)
        candidates = (set(self._ready_indices(engines))
                      if health._enabled else None)
        matches = [e.prefix_match_len(prompt) for e in engines]
        best = max(matches)
        # affinity tier: longest usable match wins outright (the fork it
        # unlocks is worth far more than perfect load spread); load (and
        # the rotation) break ties and order the no-match fallback.
        # QoS active: class-aware placement slots in BETWEEN affinity and
        # load — an interactive session avoids batch-heavy replicas (its
        # TTFT should not queue behind a flood it will only preempt), a
        # batch session packs onto them (keeps interactive replicas
        # clean, and co-locating batch work concentrates the preemption
        # victims where the park region already absorbs them)
        reg = qos.active()
        rank = (reg.rank(kwargs.get("tenant"))
                if reg is not None else None)

        def _key(i):
            j = (i + k) % n
            if rank is None:
                return (-matches[j], engines[j].load, i)
            b = getattr(engines[j], "batch_live", 0)
            if rank < qos.BATCH_RANK:
                return (-matches[j], b, engines[j].load, i)
            return (-matches[j], engines[j].load, -b, i)

        order = sorted(range(n), key=_key)
        last_exc = None
        for i in order:
            j = (i + k) % n
            if candidates is not None and j not in candidates:
                continue
            eng = engines[j]
            try:
                stream = eng.submit(prompt, **kwargs)
            except (QueueFullError, ServerClosedError) as e:
                # ServerClosedError: the snapshot can race a concurrent
                # scale_to shrink — a replica mid-drain must fail over
                # like a full one, not surface to the caller while
                # healthy replicas have capacity
                last_exc = e
                continue
            if telemetry._enabled:
                telemetry.counter("serving.generation.routed").inc()
                if best > 0 and matches[j] == best:
                    telemetry.counter(
                        "serving.generation.routed_affinity").inc()
                else:
                    telemetry.counter(
                        "serving.generation.routed_load").inc()
            if health._enabled and best > 0 and matches[j] == best:
                health.event("router_affinity", engine=eng.health_name,
                             matched=int(matches[j]),
                             prompt_tokens=int(len(prompt)))
            return stream
        raise last_exc if last_exc is not None else QueueFullError(
            "every generation replica is saturated")

    def generate(self, prompt, **kwargs):
        """Blocking convenience: route, then collect the full token list."""
        return list(self.submit(prompt, **kwargs))

    def rebalance_parked(self, max_n=None):
        """Migrate parked (preempted) sessions to peer replicas with
        spare capacity: eject each source's park records
        (:meth:`GenerationEngine.eject_parked`) and :meth:`adopt` them on
        the least-loaded OTHER replica — the session's full context
        re-prefills there and its original stream keeps delivering,
        greedy bit-exact with a fresh submit of that context. A record
        nobody can place falls back to the SOURCE replica's own queue;
        only when even that refuses does the stream fail in-band
        (never-strand). Call under sustained single-replica pressure —
        e.g. from the autoscale callback after a grow. Returns the
        number of sessions migrated to a peer."""
        engines = self.engines
        if len(engines) < 2:
            return 0
        migrated = 0
        for src in engines:
            if getattr(src, "parked_count", 0) == 0:
                continue
            for rec in src.eject_parked(max_n):
                placed = None
                peers = sorted((e for e in engines if e is not src),
                               key=lambda e: e.load)
                for dst in peers:
                    if dst.adopt(rec):
                        placed = dst
                        migrated += 1
                        break
                if placed is None and not src.adopt(rec):
                    exc = QueueFullError(
                        "no replica could adopt the preempted session")
                    rec["stream"]._fail(exc)
                    if rec.get("span") is not None:
                        rec["span"].set(error=repr(exc),
                                        reason="migrate").finish()
                    continue
                if placed is not None:
                    if telemetry._enabled:
                        telemetry.counter(
                            "serving.generation.qos.migrated").inc()
                    if health._enabled:
                        health.event("qos_migrate",
                                     source=src.health_name,
                                     target=placed.health_name,
                                     tenant=rec.get("tenant") or "default",
                                     tokens=len(rec["tokens"]))
        return migrated

    # -- autoscale actuator --------------------------------------------------

    def scale_to(self, n, reason="manual", warm=True):
        """Resize the fleet to ``n`` replicas (clamped to
        ``[min_engines, max_engines]``). Growth constructs engines from
        the registered ``factory`` (and warms them, so a scaled-up
        replica never cold-compiles under traffic); shrink pops the
        newest replicas, stops placing on them immediately and drains
        them in a background thread (``close()`` — live AND queued
        sessions finish, zero drops). Returns the new fleet size.
        Journaled as ``autoscale_actuate`` health events. A closed
        router refuses to scale (returns the current size) — a late
        autoscale signal must never resurrect a shut-down fleet."""
        if self._closed:
            return len(self.engines)
        n = max(int(n), self._min)
        if self._max is not None:
            n = min(n, self._max)
        grown, drained = [], []
        with self._scale_lock:
            with self._lock:
                need = n - len(self._engines)
            if need > 0 and self._factory is None:
                raise MXNetError(
                    "GenerationRouter.scale_to needs an engine "
                    "factory to grow the fleet")
            for _ in range(max(need, 0)):
                # construct AND warm before publishing: an unwarmed
                # replica sorts first by load and a submit racing the
                # grow would pay its cold compiles on the serving path
                eng = self._factory()
                if warm:
                    eng.warm()
                if self._ws_current is not None:
                    # the factory closure captures the params the fleet was
                    # CONSTRUCTED with; after a rollout the live version is
                    # newer — bring the fresh replica onto it before it
                    # takes traffic (same shapes: zero compiles)
                    eng.swap_weights(self._ws_current,
                                     version=self._ws_current.version)
                grown.append(eng)
            with self._lock:
                self._engines.extend(grown)
                while len(self._engines) > n:
                    drained.append(self._engines.pop())
        for eng in drained:
            t = threading.Thread(target=eng.close, daemon=True,
                                 name="mxnet_tpu.serving.generation.drain")
            t.start()
            with self._lock:
                self._draining.append((eng, t))
        if grown or drained:
            if telemetry._enabled:
                telemetry.gauge("serving.generation.replicas").set(n)
            if health._enabled:
                health.event("autoscale_actuate", replicas=n,
                             grown=len(grown), drained=len(drained),
                             reason=reason)
        # reap finished drain threads (bounded: one entry per shrink)
        with self._lock:
            self._draining = [(e, t) for e, t in self._draining
                              if t.is_alive()]
        return n

    def bind_autoscale(self):
        """Wire :func:`mxnet_tpu.health.on_autoscale` to
        :meth:`scale_to`: whenever the computed ``desired_engines``
        changes, the fleet actually grows or drains (single-host
        actuator; the callback runs on the SLO evaluation thread —
        growth warms synchronously there, off every serving path).
        The hook holds the router WEAKLY and goes inert once the router
        closes or is collected — `health.on_autoscale` has no removal
        API and its callback list outlives any one fleet, so a strong
        closure would both leak the router and let a post-shutdown
        signal construct fresh engines nobody ever closes. Returns the
        callback for tests/bookkeeping."""
        wr = weakref.ref(self)

        def _actuate(desired, info):
            router = wr()
            if router is not None and not router._closed:
                router.scale_to(desired, reason="signal")

        return health.on_autoscale(_actuate)

    # -- rolling weight swap -------------------------------------------------

    @staticmethod
    def _swap_burn():
        """Worst short-window SLO burn rate across objectives, or None
        when health is off / no objective has data yet. This is the
        rollout gate: burn > 1 means the error budget is being spent
        faster than the SLO allows — the swap made things worse."""
        if not health._enabled:
            return None
        tracker = health.tracker()
        if tracker is None:
            return None
        report = tracker.evaluate()
        burns = [o.get("burn_short") for o in report.get("objectives", [])
                 if o.get("burn_short") is not None]
        return max(burns) if burns else None

    def _pin_baseline(self):
        """First swap on this fleet: snapshot replica 0's live weights as
        the rollback target (the router was handed engines, not a
        WeightSet — without this a breached first rollout would have
        nothing to roll back TO)."""
        from ..rollout import WeightSet
        engines = self.engines
        if not engines:
            return None
        version, params, draft = engines[0].weights_snapshot()
        return WeightSet(version, params, draft_params=draft,
                         source="fleet-baseline")

    def rolling_swap(self, weights, draft_params=None, version=None,
                     observe_s=None, gate=None, rollback=True,
                     reason="publish"):
        """Flip the fleet to new weights one replica at a time, gated on
        the SLO burn tracker.

        ``weights`` is a :class:`~mxnet_tpu.serving.rollout.WeightSet`
        (a subscriber ingest) or a plain name->array dict. After each
        replica flips, the router waits ``observe_s`` seconds (default
        ``MXNET_ROLLOUT_POLL_S``) and reads the worst short-window burn
        rate; burn above ``gate`` (default ``MXNET_ROLLOUT_SLO_GATE``)
        aborts the roll and — with ``rollback=True`` — swaps every
        already-flipped replica back to the pinned previous version,
        journaled as ``rollout_rollback``. Runs under the scale lock, so
        a roll never interleaves with a concurrent grow/drain (a replica
        grown later picks the fleet's current version up in
        :meth:`scale_to`). Per-replica progress is journaled as
        ``rollout_roll`` events. Returns a report dict.
        """
        if self._closed:
            raise MXNetError("rolling_swap on a closed router")
        from ..rollout import WeightSet
        if gate is None:
            gate = float(getenv("MXNET_ROLLOUT_SLO_GATE"))
        if observe_s is None:
            observe_s = float(getenv("MXNET_ROLLOUT_POLL_S"))
        with self._scale_lock:
            if self._ws_current is None:
                self._ws_current = self._pin_baseline()
            if isinstance(weights, WeightSet):
                target = weights
                if version is None:
                    version = target.version
            else:
                if version is None:
                    version = (self._ws_current.version
                               if self._ws_current is not None else 0) + 1
                target = WeightSet(version, dict(weights),
                                   draft_params=draft_params, source=reason)
            previous = self._ws_current
            engines = self.engines
            report = {"version": int(version), "replicas": len(engines),
                      "swapped": 0, "noops": 0, "rolled_back": False,
                      "burn": None,
                      "previous_version": (previous.version
                                           if previous is not None else None)}
            flipped = []
            breach = None
            for i, eng in enumerate(engines):
                v = eng.swap_weights(target, draft_params=draft_params,
                                     version=version)
                if v is None:
                    report["noops"] += 1
                    continue
                flipped.append(eng)
                report["swapped"] += 1
                if health._enabled:
                    health.event("rollout_roll", engine=eng.health_name,
                                 index=i, version=int(version),
                                 replicas=len(engines))
                if observe_s > 0:
                    time.sleep(observe_s)
                burn = self._swap_burn()
                if burn is not None:
                    report["burn"] = float(burn)
                    if burn > gate:
                        breach = float(burn)
                        break
            if breach is not None and rollback and previous is not None:
                # roll every flipped replica back to the pinned previous
                # version — same buffer-substitution path, so the rollback
                # itself is also zero-compile and zero-downtime
                for eng in flipped:
                    eng.swap_weights(previous, version=previous.version)
                report["rolled_back"] = True
                telemetry.counter("rollout.rollbacks").inc()
                if health._enabled:
                    health.event("rollout_rollback", version=int(version),
                                 restored=int(previous.version),
                                 burn=breach, gate=float(gate),
                                 replicas_hit=len(flipped))
                # _ws_current stays `previous`: a later publish (or a
                # re-roll of the same version) starts from the restored
                # baseline — rollback-of-a-rollback converges here
                return report
            if report["swapped"]:
                if (self._ws_previous is not None
                        and self._ws_previous is not previous):
                    self._ws_previous.release()
                self._ws_previous = previous
                self._ws_current = target.acquire()
                telemetry.counter("rollout.rolls").inc()
                telemetry.gauge("rollout.fleet_version").set(int(version))
            return report

    # -- lifecycle -----------------------------------------------------------

    def warm(self, buckets=None):
        """Warm every replica (each compiles its own executables); sums
        the compile counts — ``serving.warmup`` reports through this."""
        out = {"buckets": None, "compiles": 0, "seconds": 0.0,
               "cache_entries": 0}
        for e in self.engines:
            w = e.warm(buckets)
            out["buckets"] = w["buckets"]
            out["compiles"] += w["compiles"]
            out["seconds"] += w["seconds"]
            out["cache_entries"] += w["cache_entries"]
        return out

    def close(self, timeout=None):
        self._closed = True          # gates scale_to + the autoscale hook
        for e in self.engines:
            e.close(timeout)
        with self._lock:
            draining, self._draining = self._draining, []
        for _, t in draining:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self):
        engines = self.engines
        return {"replicas": len(engines),
                "loads": [e.load for e in engines],
                "engines": [e.stats() for e in engines]}
