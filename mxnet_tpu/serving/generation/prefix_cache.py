"""RadixPrefixCache — refcounted radix trie over prompt tokens whose
payloads are KV rows living IN the engine's slot slab.

A fleet serving millions of users sees the same system prompt thousands of
times; without this module every session pays a full prefill for it. The
cache makes shared prefixes a one-time cost:

* **structure** — a radix (compressed) trie keyed on prompt token
  sequences. Edges hold token subsequences; a node with a *payload* owns
  one slab slot whose rows ``[0, length)`` are the K/V of that node's full
  prefix. Because the K/V of a prefix is a prefix of the K/V, a prompt
  that diverges MID-edge from a cached entry still hits: the longest
  common prefix of the prompt with *any* entry is usable, served by any
  payload slot in the subtree below the divergence point (every entry
  down there shares those first ``m`` tokens).
* **in-slab payloads** — cached entries occupy ordinary slots of the
  engine's existing KV slab, not a second allocation: a hit is ONE traced
  fork executable (``dynamic_slice`` + ``dynamic_update_slice`` copying
  the source slot's rows to the session's slot, compiled once) followed by
  a suffix prefill of only the unmatched tail. The memory census therefore
  keeps attributing every cached row to the ``kv_cache`` category it
  already tracks — same buffers, no double count.
* **refcounts + LRU** — ``acquire``/``release`` pin an entry while a fork
  is reading its slot (an eviction mid-copy would hand the row to a new
  prefill); eviction is LRU over refcount-ZERO entries only, runs when the
  ENGINE needs a slot for a live session (sessions always outrank cache),
  and is journaled through the health event ring (``prefix_evict``) so a
  thrashing cache is visible in ``/events``.

The trie itself is host-side metadata (a few hundred bytes per entry);
all device bytes stay in the slab. Thread-safe: the router's
prefix-affinity probe calls :meth:`match_len` from submitter threads while
the engine's tick loop mutates entries.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ... import analysis
from ... import health
from ... import telemetry

__all__ = ["RadixPrefixCache"]


def _common_len(edge, tail):
    """Token-wise common-prefix length of an edge with a prompt tail
    (compared over the shorter of the two)."""
    k = min(len(edge), len(tail))
    eq = edge[:k] == tail[:k]
    return k if bool(np.all(eq)) else int(np.argmin(eq))


class _Node:
    """One radix-trie node: ``edge`` tokens lead here from the parent;
    ``slot`` (when not None) is the slab slot holding this prefix's KV."""

    __slots__ = ("edge", "parent", "children", "length", "slot", "refs",
                 "last_used", "payloads", "version")

    def __init__(self, edge, parent, length):
        self.edge = edge              # np.int32 [e] tokens from parent
        self.parent = parent
        self.children = {}            # first token -> _Node
        self.length = length          # total prefix tokens at this node
        self.slot = None              # payload slab slot (None = internal)
        self.refs = 0                 # active borrowers (forks in flight)
        self.last_used = 0.0          # LRU clock (payload nodes)
        self.payloads = 0             # payload nodes in subtree incl. self
        self.version = 0              # weights version the KV was computed
        #                               under (rollout: a fork must never
        #                               attend old-weight KV with new-weight
        #                               logits)


class RadixPrefixCache:
    """Refcounted radix prefix cache over one engine's slot slab.

    ``metric_prefix`` scopes the telemetry counters
    (``<prefix>.prefix.{hits,misses,inserts,forks,evictions}`` and the
    ``<prefix>.prefix.cached_tokens`` gauge); ``owner`` labels health
    journal entries.
    """

    def __init__(self, metric_prefix="serving.generation", owner=""):
        self._root = _Node(np.zeros(0, np.int32), None, 0)
        self._slots = {}              # slot -> payload _Node
        self._lock = analysis.make_rlock("generation.prefix_cache")
        self._prefix = metric_prefix
        self._owner = owner

    # -- introspection -------------------------------------------------------

    def __len__(self):
        """Number of cached entries (payload nodes)."""
        with self._lock:
            return len(self._slots)

    def slots(self):
        """The slab slots the cache currently owns (the engine subtracts
        these from its free list)."""
        with self._lock:
            return set(self._slots)

    def cached_tokens(self):
        """Total real KV rows pinned across entries (the
        ``prefix.cached_tokens`` gauge)."""
        with self._lock:
            return sum(n.length for n in self._slots.values())

    def entries(self):
        """[(prefix_length, slot, refs)] for tests/debugging."""
        with self._lock:
            return sorted((n.length, s, n.refs)
                          for s, n in self._slots.items())

    # -- matching ------------------------------------------------------------

    def _walk(self, prompt):
        """Longest token match: returns (deepest fully-entered node,
        matched token count). The match may end mid-edge; ``node`` is the
        last node whose subtree contains every entry sharing the match."""
        node = self._root
        m = 0
        n = len(prompt)
        while m < n:
            child = node.children.get(int(prompt[m]))
            if child is None:
                return node, m
            e = child.edge
            eq = _common_len(e, prompt[m:])
            m += eq
            if eq < len(e):
                # diverged (or prompt ended) mid-edge: every entry below
                # `child` still shares the first m tokens
                return child, m
            node = child
        return node, m

    def _payload_below(self, node, version=None):
        """Any payload node at or below ``node`` stamped with weights
        ``version`` (None = any), depth-first through subtrees that
        report payloads."""
        stack = [node]
        while stack:
            n = stack.pop()
            if n.slot is not None and (version is None
                                       or n.version == version):
                return n
            stack.extend(c for c in n.children.values() if c.payloads)
        return None

    def match(self, prompt, version=None):
        """Longest usable cached prefix of ``prompt``: returns
        ``(payload_node, matched_len)`` or ``(None, 0)``. The matched
        length is capped at ``len(prompt) - 1`` — at least one suffix
        token must remain to produce the first sampled logits. With
        ``version`` only entries stamped with that weights version
        qualify (the engine passes its current version, so a post-swap
        fork can never splice old-weight KV under new-weight logits).
        Does NOT count telemetry or touch LRU; callers decide (the
        router probes without consuming)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            node, m = self._walk(prompt)
            m = min(m, prompt.size - 1)
            if m <= 0:
                return None, 0
            pay = self._payload_below(node, version)
            if pay is None:
                return None, 0
            return pay, m

    def match_len(self, prompt, version=None):
        """Matched token count only (the router's affinity probe)."""
        _, m = self.match(prompt, version)
        return m

    def acquire(self, node):
        """Pin ``node`` against eviction (a fork is about to read its
        slot) and touch its LRU clock."""
        with self._lock:
            node.refs += 1
            node.last_used = time.monotonic()

    def release(self, node):
        with self._lock:
            node.refs = max(node.refs - 1, 0)

    # -- insertion -----------------------------------------------------------

    def insert(self, prompt, slot, version=0):
        """Register ``slot`` as holding the KV of the full ``prompt``
        prefix, stamped with the weights ``version`` it was computed
        under. Returns the payload node, or None when the exact prefix is
        already cached at the same version (the caller keeps its slot
        free — dedupe, don't hoard); an entry cached under a DIFFERENT
        version is replaced, its old-weight rows dropped. Splits edges at
        divergence points; split nodes are internal (payload-less) until
        some insert lands exactly there."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            return None
        with self._lock:
            node = self._root
            m = 0
            n = prompt.size
            while m < n:
                child = node.children.get(int(prompt[m]))
                if child is None:
                    child = _Node(prompt[m:].copy(), node, n)
                    node.children[int(prompt[m])] = child
                    node = child
                    m = n
                    break
                e = child.edge
                eq = _common_len(e, prompt[m:])
                if eq < len(e):
                    # split the edge at the divergence point
                    mid = _Node(e[:eq].copy(), node, child.length
                                - (len(e) - eq))
                    node.children[int(e[0])] = mid
                    child.edge = e[eq:].copy()
                    child.parent = mid
                    mid.children[int(child.edge[0])] = child
                    mid.payloads = child.payloads
                    node = mid
                else:
                    node = child
                m += eq
            if node.slot is not None:
                if node.version == int(version):
                    node.last_used = time.monotonic()  # already cached: touch
                    return None
                # same prefix, different weights: the cached rows are
                # stale logits-wise — replace the payload outright (no
                # pruning: the node immediately carries the new payload)
                self._drop_payload(node, "version_replace", prune=False)
            node.slot = int(slot)
            node.version = int(version)
            node.last_used = time.monotonic()
            self._slots[int(slot)] = node
            p = node
            while p is not None:
                p.payloads += 1
                p = p.parent
            if telemetry._enabled:
                telemetry.counter(f"{self._prefix}.prefix.inserts").inc()
                telemetry.gauge(f"{self._prefix}.prefix.cached_tokens").set(
                    self.cached_tokens())
            return node

    # -- eviction ------------------------------------------------------------

    def _drop_payload(self, node, reason, prune=True):
        slot = node.slot
        tokens = int(node.length)
        node.slot = None
        del self._slots[slot]
        p = node
        while p is not None:
            p.payloads -= 1
            p = p.parent
        # prune now-useless leaf chains so the trie stays O(entries) —
        # skipped when the caller is about to repopulate the same node
        # (version_replace re-inserts in place)
        while (prune and node is not self._root and node.slot is None
               and not node.children):
            parent = node.parent
            del parent.children[int(node.edge[0])]
            node = parent
        if telemetry._enabled:
            telemetry.counter(f"{self._prefix}.prefix.evictions").inc()
            telemetry.gauge(f"{self._prefix}.prefix.cached_tokens").set(
                self.cached_tokens())
        if health._enabled:
            health.event("prefix_evict", engine=self._owner, slot=slot,
                         tokens=tokens, reason=reason)
        return slot

    def evict_lru(self, reason="pressure"):
        """Free the least-recently-used refcount-ZERO entry's slot and
        return it (None when every entry is pinned or the cache is
        empty). The engine calls this when a session needs a slot and
        none is free — live sessions always outrank cached prefixes."""
        with self._lock:
            victim = None
            for node in self._slots.values():
                if node.refs == 0 and (victim is None
                                       or node.last_used < victim.last_used):
                    victim = node
            if victim is None:
                return None
            return self._drop_payload(victim, reason)

    def evict_slot(self, slot, reason="explicit"):
        """Drop the entry holding ``slot`` (tests, engine teardown).
        Returns True when an entry was dropped."""
        with self._lock:
            node = self._slots.get(int(slot))
            if node is None:
                return False
            self._drop_payload(node, reason)
            return True

    def evict_other_versions(self, version, reason="weights_swap"):
        """Drop every entry NOT stamped with weights ``version`` (the
        engine calls this at swap time: entries computed under the old
        weights would otherwise serve forks whose prefix logits no
        longer match the model). Returns the number dropped."""
        with self._lock:
            victims = [s for s, n in self._slots.items()
                       if n.version != int(version)]
            for slot in victims:
                self.evict_slot(slot, reason)
            return len(victims)

    def clear(self, reason="clear"):
        """Drop every entry (engine slab reallocation after a failed tick
        — the copied rows died with the donated buffers)."""
        with self._lock:
            for slot in list(self._slots):
                self.evict_slot(slot, reason)

    def stats(self):
        with self._lock:
            return {"entries": len(self._slots),
                    "cached_tokens": self.cached_tokens(),
                    "slots": sorted(self._slots)}
