"""GenerationStream — the client half of one autoregressive session.

``engine.submit(prompt)`` returns one of these immediately; the engine's
continuous scheduler then delivers tokens into it as they are decoded.
Two consumption styles:

* **streaming** — iterate the stream: each ``__next__`` yields the next
  generated token as soon as it exists. A blocking iterator is also a
  CALLER-RUNS assistant (the batcher's trick, PR 5): while its token
  queue is empty it tries to run engine ticks inline instead of parking
  behind two thread handoffs, so a single closed-loop client is not
  throttled by worker wakeup latency.
* **collecting** — ``result(timeout)`` blocks for the complete token list
  (a ``concurrent.futures.Future`` under the hood — this is also the
  future the admission queue watches, so a stream failed while queued is
  dropped unadmitted).

Failure surfaces in-band: a session evicted on deadline raises
:class:`~mxnet_tpu.serving.admission.DeadlineExceededError` from the
iterator (and from ``result()``) instead of wedging it; engine errors
raise the original exception the same way.
"""
from __future__ import annotations

import queue
import time
from concurrent.futures import Future

__all__ = ["GenerationStream"]

_TOK, _END, _ERR = 0, 1, 2


class GenerationStream:
    """Iterator of generated tokens for one submitted prompt."""

    def __init__(self, engine, prompt_len, max_new_tokens, deadline=None,
                 tenant=None):
        self._engine = engine       # reassigned when a preempted session
        #                             migrates to a peer replica (the
        #                             caller-runs assist then drives the
        #                             adopting engine's ticks)
        self._q = queue.Queue()
        self._future = Future()
        self._stop = False          # iterator-side: terminal item consumed
        self.tokens = []            # delivered so far (engine appends)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.tenant = tenant        # QoS tenant name (None = default class)
        self.submitted_at = time.monotonic()
        self.first_token_at = None
        # set at admission when the engine forked a cached prompt prefix
        # instead of running a full prefill: the number of prompt tokens
        # whose K/V came from the prefix cache (0 = full prefill) — the
        # client-visible "why was my TTFT fast" signal
        self.cached_prefix_len = 0

    # -- engine side ---------------------------------------------------------

    def _push(self, tok):
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self.tokens.append(tok)
        self._q.put((_TOK, tok))

    def _finish(self):
        if not self._future.done():
            self._future.set_result(list(self.tokens))
        self._q.put((_END, None))

    def _fail(self, exc):
        if not self._future.done():
            self._future.set_exception(exc)
        self._q.put((_ERR, exc))

    # -- client side ---------------------------------------------------------

    @property
    def done(self):
        """True once the session reached a terminal state (all tokens
        delivered, or failed)."""
        return self._future.done()

    def result(self, timeout=None):
        """Block for the COMPLETE generation: the list of all generated
        tokens (raises the failure exception for failed sessions)."""
        return self._future.result(timeout)

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop:
            raise StopIteration
        while True:
            try:
                kind, val = self._q.get_nowait()
                break
            except queue.Empty:
                # caller-runs assist: drive the engine inline while our
                # queue is empty; when another thread holds the tick lock
                # (the worker mid-tick), park briefly on the queue instead
                if not self._engine._assist_once():
                    try:
                        kind, val = self._q.get(timeout=0.005)
                        break
                    except queue.Empty:
                        continue
        if kind == _TOK:
            return val
        self._stop = True
        if kind == _ERR:
            raise val
        raise StopIteration
