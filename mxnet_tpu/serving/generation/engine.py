"""GenerationEngine — token-level continuous batching over a KV slot slab.

PR 5's :class:`~mxnet_tpu.serving.batcher.DynamicBatcher` schedules at
REQUEST granularity: a batch forms, computes once, and every member leaves
together. Autoregressive generation breaks that shape — sessions are
hundreds of sequential single-token steps of wildly different counts, so
request-level batching would hold every finished sequence hostage to the
longest one (and re-running the full forward per token would cost O(T) per
token, O(T²) per sequence). This engine is the token-level scheduler:

* **slot-based session store** — a preallocated KV slab
  ``[max_slots, layers, heads, max_len, head_dim]``
  (:meth:`TransformerLM.init_cache`) whose shape NEVER changes: admitting
  a session is a prefill write into a free slot index, evicting is
  clearing host-side metadata — continuous batching without a recompile,
  ever (the arXiv:2603.09555 compile-once O(1)-cache discipline).
* **continuous scheduling** — every engine tick runs ONE fused
  ``decode_step`` over the whole slab (all live sessions advance one
  token together), evicts finished/EOS/deadline-expired sessions, and
  admits queued prefills into the freed slots mid-stream. The intake is
  PR 5's :class:`~mxnet_tpu.serving.admission.AdmissionQueue`
  (``QueueFullError`` backpressure, ``ServerClosedError`` after close,
  per-session deadlines swept per tick via ``expire()``), prompts pad up
  a prefill-length bucket ladder, and a blocking stream iterator assists
  caller-runs style.
* **prefix cache + in-slab KV forking** — with
  ``MXNET_GENERATION_PREFIX_CACHE=1`` a refcounted radix trie
  (:mod:`.prefix_cache`) maps prompt prefixes to slab slots holding their
  K/V. Admission of a prompt whose prefix is cached runs ONE traced fork
  executable (``dynamic_slice`` + ``dynamic_update_slice`` copying the
  source slot's rows) and prefills only the unmatched suffix
  (:meth:`TransformerLM.prefill_at`) — a fleet-shared system prompt
  prefills once, then every later session pays O(suffix). Sessions
  always outrank cached entries for slots (LRU eviction of refcount-zero
  entries on admission pressure, journaled through the health ring).
* **speculative decoding** — with ``MXNET_GENERATION_SPEC_K=k`` a draft
  (:mod:`.speculative`: ``MXNET_GENERATION_DRAFT`` checkpoint or the
  n-gram fallback) proposes k tokens per live slot per tick and ONE
  fixed-shape slab-wide verify executable
  (:meth:`TransformerLM.verify_step` — k+1 unrolled decode graphs, so
  greedy output is BIT-EXACT with the plain path) checks them all;
  the engine commits the longest agreeing draft prefix plus the target's
  own next token (1 to k+1 tokens per tick) and rolls the rest back by
  simply not advancing the slot's position — rejected rows beyond the
  frontier are never attended and are overwritten before they could be.
* **compile discipline** — one ``CompileCache("generation")`` entry per
  prefill bucket plus exactly ONE decode (or verify) executable — and,
  per enabled feature, one fork entry, one suffix-prefill entry per
  bucket and the draft's own pinned set — all with the slab buffers
  donated (``persistent=False``: donated programs stay out of the
  on-disk XLA cache, the PR 3 aliasing rule). ``serving.warmup`` pins the
  exact count ahead of traffic; steady state compiles nothing.

Telemetry rides ``serving.generation.*`` (live-slot gauge, tokens/s,
TTFT/tick histograms, per-reason eviction counters, derived
``slot_fill_ratio``, plus ``prefix.{hits,misses,forks,inserts,
evictions}``/``prefix.cached_tokens`` and ``spec.{proposed,accepted,
rolled_back,committed}`` with derived ``spec.acceptance_ratio``); tracing
builds one span tree per session (root → queued → fork/prefill → decode
ticks → evict); the slab (and the checkpoint draft's slab) registers
under the ``kv_cache`` memory-census category — forked rows live inside
the same slab buffers, so the census never double-counts them.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ... import analysis
from ... import health
from ... import memory
from ... import observatory
from ... import telemetry
from ... import tracing
from ...base import MXNetError, getenv, register_env
from ...compile_cache import CompileCache
from ...io import staging as _staging
from ...log import get_logger
from .. import qos
from ..admission import AdmissionQueue, DeadlineExceededError, Request
from ..health import attach_engine, queue_ready
from . import speculative
from .prefix_cache import RadixPrefixCache
from .session import GenerationStream

__all__ = ["GenerationEngine", "prefill_ladder"]

register_env("MXNET_GENERATION_SLOTS", 8,
             "KV-slab slot count per generation engine: the max number of "
             "concurrently-decoding sessions (one fused decode_step covers "
             "the whole slab each tick)")
register_env("MXNET_GENERATION_MAX_LEN", 256,
             "KV-slab sequence capacity per slot (prompt + generated "
             "tokens); bounds per-slot HBM at "
             "2*layers*heads*max_len*head_dim*dtype bytes")
register_env("MXNET_GENERATION_PREFILL_BUCKETS", "",
             "prefill-length bucket ladder (comma-separated ints, each a "
             "compiled prefill program); empty = powers of two from 8 up "
             "to MXNET_GENERATION_MAX_LEN")
register_env("MXNET_GENERATION_TICK_BUDGET_MS", 10.0,
             "max milliseconds one scheduler tick spends admitting queued "
             "prefills before the fused decode runs again (>= 1 admission "
             "per tick when slots are free, so queues always drain)")
register_env("MXNET_GENERATION_PREFIX_CACHE", False,
             "cache prompt-prefix KV in free slab slots (refcounted radix "
             "trie): admission of a prompt with a cached prefix runs one "
             "traced slot-to-slot fork + a suffix-only prefill instead of "
             "a full-prompt prefill")
register_env("MXNET_GENERATION_PREFIX_MIN_TOKENS", 8,
             "shortest prompt prefix worth forking from (or inserting "
             "into) the prefix cache — below this a full prefill is "
             "cheaper than the fork dispatch")


def prefill_ladder(buckets, max_len):
    """Normalize a prefill bucket spec (None ->
    ``MXNET_GENERATION_PREFILL_BUCKETS``; empty -> powers of two up to
    ``max_len``) into an ascending tuple capped at ``max_len`` —
    spec parsing/validation shared with the predictor's
    :func:`~mxnet_tpu.serving.predictor.bucket_ladder`."""
    from ..predictor import bucket_ladder

    if buckets is None:
        buckets = getenv("MXNET_GENERATION_PREFILL_BUCKETS")
    if not (buckets.strip() if isinstance(buckets, str) else buckets):
        b, buckets = 8, []
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    out = bucket_ladder(buckets, env_var="MXNET_GENERATION_PREFILL_BUCKETS")
    return tuple(sorted({min(int(b), int(max_len)) for b in out}))


class _Session:
    """Engine-side state of one admitted (or queued) generation."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "deadline", "stream",
                 "span", "slot", "generated", "prefix_len", "version",
                 "tenant", "qos_rank", "admit_seq")

    def __init__(self, prompt, max_new_tokens, eos_id, deadline, stream,
                 tenant=None):
        self.prompt = prompt            # np.int32 [n]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.deadline = deadline
        self.stream = stream
        self.span = None                # tracing root (MXNET_TRACING=1)
        self.slot = None
        self.generated = 0
        self.prefix_len = 0             # cached tokens forked at admission
        self.version = 0                # weights version pinned at admission
        #                                 (rollout: the session finishes
        #                                 bit-exact on these weights even
        #                                 after a swap)
        self.tenant = tenant            # QoS tenant (None = default class)
        self.qos_rank = None            # class rank stamped at admission
        self.admit_seq = 0              # admission order: the preemptor
        #                                 parks the YOUNGEST batch session


class GenerationEngine:
    """Continuous-batching autoregressive server over one model replica.

    Parameters
    ----------
    model : TransformerLM
        Functional model providing ``init_cache`` / ``prefill`` /
        ``decode_step`` (pure, jit-able, cache-donating).
    params : dict[str, jax.Array]
        The model's parameters (``init_params`` placement).
    max_slots / max_len / buckets / tick_budget_ms :
        Overrides of the ``MXNET_GENERATION_*`` knobs.
    max_queue : int, optional
        Intake bound (default ``MXNET_SERVING_MAX_QUEUE``).
    eos_id : int, optional
        Default end-of-sequence token for sessions that don't pass one.
    start : bool
        Spin the scheduler worker thread (tests drive ticks manually with
        ``False``).
    prefix_cache / prefix_min_tokens :
        Overrides of ``MXNET_GENERATION_PREFIX_CACHE`` /
        ``_PREFIX_MIN_TOKENS`` — cache prompt-prefix KV in free slab
        slots and admit matching prompts via fork + suffix prefill.
    spec_k : int, optional
        Override of ``MXNET_GENERATION_SPEC_K`` — draft length for the
        speculative verify lane (0 = plain one-token decode). The slab
        grows ``spec_k`` scratch rows so a near-capacity slot's verify
        writes stay in bounds, which costs ``spec_k`` positions of the
        model's range: ``max_len`` is clamped to ``cfg.max_len - spec_k``.
    draft : Draft, optional
        The draft model for the speculative lane (default: a
        ``CheckpointDraft`` from ``MXNET_GENERATION_DRAFT``, else the
        n-gram fallback).
    """

    def __init__(self, model, params, max_slots=None, max_len=None,
                 buckets=None, max_queue=None, tick_budget_ms=None,
                 eos_id=None, start=True, prefix_cache=None,
                 prefix_min_tokens=None, spec_k=None, draft=None):
        self._model = model
        self._params = params
        self._slots = int(getenv("MXNET_GENERATION_SLOTS")
                          if max_slots is None else max_slots)
        self._spec_k = int(getenv("MXNET_GENERATION_SPEC_K")
                           if spec_k is None else spec_k)
        if self._spec_k < 0:
            raise MXNetError(f"spec_k must be >= 0, got {self._spec_k}")
        self._max_len = int(getenv("MXNET_GENERATION_MAX_LEN")
                            if max_len is None else max_len)
        self._max_len = min(self._max_len, model.cfg.max_len - self._spec_k)
        if self._max_len < 2:
            raise MXNetError(
                f"max_len {self._max_len} after reserving {self._spec_k} "
                f"speculative scratch rows from the model's positional "
                f"range {model.cfg.max_len} — lower MXNET_GENERATION_SPEC_K")
        # the slab carries spec_k scratch rows past session capacity: a
        # verify block starting at the last legal position writes k rows
        # past it, and those writes must land somewhere no session owns
        self._slab_len = self._max_len + self._spec_k
        if self._slots < 1:
            raise MXNetError(f"need >= 1 slot, got {self._slots}")
        self._buckets = prefill_ladder(buckets, self._max_len)
        budget_ms = (getenv("MXNET_GENERATION_TICK_BUDGET_MS")
                     if tick_budget_ms is None else tick_budget_ms)
        self._tick_budget_s = float(budget_ms) / 1e3
        self._eos_id = eos_id
        self._logger = get_logger("mxnet_tpu.serving.generation")

        self._cache = CompileCache("generation")
        # weight rollout state: _param_sets pins every weights version a
        # live session may still decode under — {version: (params, ws)}
        # where ws is the publishing WeightSet (None for construction
        # params). swap_weights() flips _params/_weights_version between
        # ticks; _gc_param_sets() releases a version once no session
        # pins it
        self._weights_version = 0
        self._param_sets = {0: (params, None)}
        # multi-tenant QoS (default-off): with a registry active the slab
        # grows MXNET_QOS_PARK_SLOTS park rows past session capacity —
        # preemption forks a batch session's KV rows into the park region
        # and resumes it later, bit-exact, through the SAME fork
        # executable. With QoS off _total_slots == _slots, so every
        # executable key (and the compile accounting) is bit-identical
        self._qos = qos.active()
        self._park = (int(getenv("MXNET_QOS_PARK_SLOTS"))
                      if self._qos is not None else 0)
        if self._park < 0:
            raise MXNetError(
                f"MXNET_QOS_PARK_SLOTS must be >= 0, got {self._park}")
        self._total_slots = self._slots + self._park
        self._parked = {}            # park slot -> {sess, length, last_tok,
        #                              parked_at}
        self._park_free = list(range(self._slots, self._total_slots))
        self._admit_seq = 0
        self._ck, self._cv = model.init_cache(self._total_slots,
                                              self._slab_len)
        # host-side slot metadata — only the tick loop (under _tick_lock)
        # mutates these
        self._sessions = [None] * self._total_slots
        self._lengths = np.zeros(self._total_slots, np.int32)
        self._last_tok = np.zeros(self._total_slots, np.int32)
        self._live = 0

        self._queue = AdmissionQueue(max_queue,
                                     metric_prefix="serving.generation")
        self._tick_lock = analysis.make_lock("generation.tick")
        self._work = analysis.make_condition("generation.work")
        self._closed = False
        self._tokens_window = 0
        self._rate_t0 = time.monotonic()
        self.sessions_submitted = 0   # per-replica intake (router balance)
        # fleet-health wiring: liveness/readiness probes (/healthz,
        # /readyz, router drain) + the scheduler-tick progress beacon the
        # stall watchdog monitors. Registration is construction-time;
        # the tick path pays one health._enabled read when the layer is
        # off (pinned by test_health.py)
        self._warmed = False          # set by warm(); ready() also
        #                               accepts traffic-compiled engines
        self.health_name, self._beacon = attach_engine(self)
        if self._qos is not None and health._enabled:
            # per-tenant TTFT burn rows join the SLO tracker once per
            # registry (idempotent across replicas)
            qos.attach_slo(self._qos)

        use_prefix = (bool(getenv("MXNET_GENERATION_PREFIX_CACHE"))
                      if prefix_cache is None else bool(prefix_cache))
        if use_prefix and getattr(model.cfg, "moe_experts", 0) > 0:
            # MoE expert capacity is computed over the forward's input
            # length, so a suffix-only prefill can capacity-drop
            # DIFFERENT tokens than the full-prompt prefill would — the
            # fork path would then diverge beyond the documented ulp
            # level depending on what the cache happened to hold. Until
            # prefill_at routes with full-prompt capacity semantics the
            # cache stays off for MoE models
            self._logger.warning(
                "prefix cache disabled: MoE capacity is length-dependent"
                " and a suffix prefill would route differently than the"
                " full prefill")
            use_prefix = False
        self._prefix_min = int(
            getenv("MXNET_GENERATION_PREFIX_MIN_TOKENS")
            if prefix_min_tokens is None else prefix_min_tokens)
        self._prefix = (RadixPrefixCache(owner=self.health_name)
                        if use_prefix else None)
        self._draft = None
        if self._spec_k:
            self._draft = (speculative.default_draft(model.mesh)
                           if draft is None else draft)
            self._draft.attach(self)

        # the slab is device state the engine REPLACES every tick, so the
        # census needs a live view, not a snapshot weakref
        memory.register_provider("kv_cache", self,
                                 lambda e: [e._ck, e._cv])

        self._worker = None
        if start:
            self._worker = threading.Thread(
                target=self._loop, daemon=True,
                name="mxnet_tpu.serving.generation.engine")
            self._worker.start()

    # -- properties ----------------------------------------------------------

    @property
    def max_slots(self):
        """Session capacity (park slots excluded — they are preemption
        headroom, never admittable)."""
        return self._slots

    @property
    def total_slots(self):
        """Slab slot count including the QoS park region — the dimension
        every slab-shaped executable and the draft's slab use."""
        return self._total_slots

    @property
    def parked_count(self):
        """Preempted sessions currently parked in the slab's park region."""
        return len(self._parked)

    @property
    def batch_live(self):
        """Live batch-class sessions — the router's class-aware placement
        signal (interactive avoids batch-heavy replicas, batch packs onto
        them). Always 0 while QoS is off."""
        if self._qos is None:
            return 0
        return sum(1 for s in self._sessions
                   if s is not None and s.qos_rank == qos.BATCH_RANK)

    @property
    def max_len(self):
        return self._max_len

    @property
    def prefill_buckets(self):
        return self._buckets

    @property
    def spec_k(self):
        """Draft length of the speculative lane (0 = plain decode)."""
        return self._spec_k

    @property
    def draft(self):
        return self._draft

    @property
    def prefix_cache(self):
        """The engine's :class:`RadixPrefixCache` (None when disabled)."""
        return self._prefix

    @property
    def weights_version(self):
        """Version of the CURRENT weight set (new admissions use it; live
        sessions keep the version they were admitted under)."""
        return self._weights_version

    @property
    def live_weight_versions(self):
        """Sorted versions some live session still decodes under plus the
        current one — >1 entry only while an old version drains after a
        swap."""
        versions = {s.version for s in self._sessions if s is not None}
        versions.add(self._weights_version)
        return sorted(versions)

    def prefix_match_len(self, prompt):
        """Longest USABLE cached prefix of ``prompt`` on this engine (0
        when below the fork threshold or the cache is off) — the router's
        affinity probe; cheap host trie walk, no device work. Only
        current-version entries count (admission forks filter the same
        way)."""
        if self._prefix is None:
            return 0
        m = self._prefix.match_len(
            np.asarray(prompt, dtype=np.int32).reshape(-1),
            version=self._weights_version)
        return m if m >= self._prefix_min else 0

    @property
    def cache(self):
        """The engine's ``"generation"`` :class:`CompileCache` — ``.misses``
        is the exact number of programs compiled so far."""
        return self._cache

    @property
    def live_slots(self):
        return self._live

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def load(self):
        """Occupancy the router balances on: (live + queued) / slots."""
        return (self._live + len(self._queue)) / float(self._slots)

    @property
    def closed(self):
        return self._closed

    # -- health --------------------------------------------------------------

    def healthy(self):
        """Liveness: (ok, detail). False only when the scheduler worker
        thread died while the engine still owes work (a closed engine's
        joined worker is fine, and manually-ticked engines have none)."""
        if (self._worker is not None and not self._worker.is_alive()
                and not self._closed):
            return False, "scheduler worker thread died"
        return True, "ok"

    def ready(self):
        """Readiness: (ok, reason) — the router's placement gate and the
        ``/readyz`` probe. Not ready while draining (closed), while the
        tick beacon is marked stalled by the watchdog, before any
        executable exists (warm() not run AND no traffic compiled one),
        or with the intake queue above the watermark."""
        if self._closed:
            return False, "closed (draining)"
        if self._beacon.stalled:
            return False, "scheduler stalled (watchdog)"
        if not self._warmed and not len(self._cache):
            return False, "warmup not run"
        return queue_ready(self._queue)

    def kv_slab_bytes(self):
        """Total device bytes the KV slab pins (both key and value
        arrays) — the number ``docs/faq/perf.md`` "Sizing the KV slab"
        budgets."""
        return int(self._ck.nbytes) + int(self._cv.nbytes)

    def bucket_for(self, n):
        for b in self._buckets:
            if b >= n:
                return b
        return None

    # -- client API ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens=64, eos_id=None, timeout=None,
               tenant=None):
        """Admit one prompt; returns a :class:`GenerationStream`
        immediately. ``timeout`` (seconds) is the SESSION deadline —
        checked every scheduler tick, in queue and mid-generation; expiry
        evicts the slot and fails the stream with
        :class:`DeadlineExceededError`. ``tenant`` names the QoS tenant
        (class/quota/weight per ``MXNET_QOS_SPEC``; ignored while QoS is
        off). Raises ``QueueFullError`` / ``ServerClosedError`` (and,
        QoS active, ``QuotaExceededError``) synchronously (backpressure
        is a signal, not a stall)."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size < 1:
            raise MXNetError("empty prompt")
        if prompt.size > self._buckets[-1]:
            raise MXNetError(
                f"prompt of {prompt.size} tokens exceeds the largest "
                f"prefill bucket {self._buckets[-1]}")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if prompt.size + int(max_new_tokens) > self._max_len:
            raise MXNetError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slab capacity "
                f"{self._max_len} (MXNET_GENERATION_MAX_LEN)")
        deadline = (time.monotonic() + float(timeout)
                    if timeout is not None else None)
        stream = GenerationStream(self, prompt.size, max_new_tokens,
                                  deadline, tenant=tenant)
        sess = _Session(prompt, max_new_tokens,
                        self._eos_id if eos_id is None else eos_id,
                        deadline, stream, tenant=tenant)
        if tracing._enabled:
            sess.span = tracing.begin("generation.session", cat="generation",
                                      prompt_tokens=int(prompt.size),
                                      max_new_tokens=int(max_new_tokens))
        req = Request([prompt], 1, stream._future, deadline=deadline,
                      payload=sess, tenant=tenant)
        try:
            self._queue.put(req)
        except Exception as e:
            if sess.span is not None:
                sess.span.set(error=repr(e)).finish()
            raise
        if telemetry._enabled:
            telemetry.counter("serving.generation.sessions").inc()
        if health._enabled:
            # work is pending: the tick beacon's silence now counts as a
            # stall until the slab drains again
            self._beacon.arm()
        with self._work:
            # under the condition lock: concurrent submitters would lose
            # increments of a bare +=
            self.sessions_submitted += 1
            self._work.notify_all()
        return stream

    def generate(self, prompt, **kwargs):
        """Blocking convenience: submit and collect the full token list
        (the iterator's caller-runs assist drives ticks inline when the
        worker is idle)."""
        return list(self.submit(prompt, **kwargs))

    def warm(self, buckets=None):
        """Compile-ahead every generation executable the enabled features
        will run, counted exactly (``cache.misses`` delta): one prefill
        program per bucket, plus — prefix cache on — one suffix-prefill
        program per bucket and THE fork program, plus THE decode program
        (plain) or THE verify program and the draft's own pinned set
        (speculative). Prefill/suffix warms write garbage into a FREE
        slot (skipped, with a log, for buckets that cannot get one on an
        already-full slab — they were compiled by real traffic anyway);
        the decode/verify warm runs only while no session is live, and
        its garbage K/V writes are steered to the slab's last row
        (:meth:`_tick_positions`), so warming a serving engine never
        perturbs a session or a cached prefix entry. Returns
        ``{"buckets", "compiles", "seconds", "cache_entries"}``."""
        import jax.numpy as jnp

        buckets = (self._buckets if buckets is None
                   else tuple(sorted({int(b) for b in buckets})))
        t0 = time.perf_counter()
        misses0 = self._cache.misses
        with self._tick_lock:
            free_list = self._free_slots()
            free = free_list[0] if free_list else None
            for b in buckets:
                if b not in self._buckets:
                    raise MXNetError(f"bucket {b} not in ladder "
                                     f"{self._buckets}")
                if free is None:
                    self._logger.warning(
                        "generation warmup: slab full, skipping prefill "
                        "warm for bucket %d", b)
                    continue
                fn = self._prefill_fn(b)
                _, self._ck, self._cv = fn(
                    self._params, self._ck, self._cv,
                    jnp.zeros((b,), jnp.int32), jnp.asarray(1, jnp.int32),
                    jnp.asarray(free, jnp.int32))
                if self._prefix is not None:
                    fn = self._suffix_prefill_fn(b)
                    _, self._ck, self._cv = fn(
                        self._params, self._ck, self._cv,
                        jnp.zeros((b,), jnp.int32),
                        jnp.asarray(1, jnp.int32),
                        jnp.asarray(free, jnp.int32),
                        jnp.asarray(0, jnp.int32))
            if (self._prefix is not None or self._park) and free is not None:
                # self-copy: compiles the fork without disturbing anything
                # (the prefix cache's admission fork AND the QoS
                # preempt/park/resume path share this one executable —
                # warming it here is what keeps preemption compile-free)
                fn = self._fork_fn()
                self._ck, self._cv = fn(self._ck, self._cv,
                                        jnp.asarray(free, jnp.int32),
                                        jnp.asarray(free, jnp.int32))
            idle = self._live == 0
            if self._spec_k:
                if idle:
                    fn = self._verify_fn()
                    _, self._ck, self._cv = fn(
                        self._params, self._ck, self._cv,
                        jnp.zeros((self._total_slots, self._spec_k + 1),
                                  jnp.int32),
                        jnp.asarray(self._tick_positions()))
                    self._draft.warm()
                else:
                    self._logger.warning(
                        "generation warmup: engine busy, skipping "
                        "verify/draft warm")
            elif idle:
                fn = self._decode_fn()
                _, self._ck, self._cv = fn(
                    self._params, self._ck, self._cv,
                    jnp.asarray(self._last_tok),
                    jnp.asarray(self._tick_positions()))
        compiles = self._cache.misses - misses0
        seconds = time.perf_counter() - t0
        self._warmed = True           # readiness: warmup complete
        if telemetry._enabled:
            telemetry.counter("serving.generation.warmup_compiles").inc(
                compiles)
        self._logger.info(
            "generation warmup: %d bucket(s) + %s -> %d compile(s) in "
            "%.2fs (cache %r holds %d executables)", len(buckets),
            "verify" if self._spec_k else "decode", compiles,
            seconds, self._cache.name, len(self._cache))
        return {"buckets": list(buckets), "compiles": compiles,
                "seconds": seconds, "cache_entries": len(self._cache)}

    # -- weight rollout ------------------------------------------------------

    def _place_params(self, new):
        """Validate and device-place one incoming host weight dict against
        the CURRENT params: same key set, same shapes, values cast to the
        current dtypes and placed with the model's partition specs — the
        guarantees that make the swap a pure buffer substitution (every
        executable key is shape-only, params are non-donated arguments,
        so the warmed decode/verify/prefill programs are reused
        untouched)."""
        import jax

        cur = self._params
        if set(new) != set(cur):
            missing = sorted(set(cur) - set(new))
            extra = sorted(set(new) - set(cur))
            raise MXNetError(
                f"swap_weights: parameter names differ from the bound set "
                f"(missing {missing}, unexpected {extra}) — a hot swap "
                "must cover exactly the bound parameters")
        specs = self._model.param_specs()
        placed = {}
        for name, v in new.items():
            old = cur[name]
            arr = np.asarray(v)
            if tuple(arr.shape) != tuple(old.shape):
                raise MXNetError(
                    f"swap_weights: parameter {name!r} has shape "
                    f"{tuple(arr.shape)} but the warmed executables "
                    f"expect {tuple(old.shape)} — identical shapes/dtypes "
                    "are what make the swap compile-free")
            placed[name] = jax.device_put(
                arr.astype(old.dtype, copy=False), specs[name])
        return placed

    def swap_weights(self, weights, draft_params=None, version=None):
        """Atomic zero-downtime weight flip, BETWEEN ticks (takes the
        tick lock): new admissions prefill and decode under the new
        weights; sessions already live keep decoding — bit-exact — under
        the version they were admitted with until they finish (the tick
        runs one executable dispatch per live version, same programs,
        positions of other cohorts steered to the slab's safe row). The
        KV slab, the radix prefix cache structure and the speculative
        draft slab all survive the flip; prefix entries stamped with
        other versions are evicted (their KV would splice old-weight
        rows under new-weight logits), and a checkpoint draft's params
        flip immediately for every slot — stale draft slab rows only
        cost acceptance ratio, never correctness (the verify is the
        ground truth).

        ``weights`` is a :class:`~..rollout.WeightSet` or a plain host
        param dict. Returns the new version, or None when ``version``
        equals the current one (idempotent double-publish no-op).
        Rolling BACK to a still-pinned older version reuses its placed
        params directly."""
        ws = None
        if hasattr(weights, "arg_params") and hasattr(weights, "version"):
            ws = weights
            version = ws.version if version is None else version
            new = dict(ws.arg_params)
            new.update(ws.aux_params)
            if draft_params is None and ws.draft_params:
                draft_params = ws.draft_params
        else:
            new = dict(weights)
        with self._tick_lock:
            if version is None:
                version = self._weights_version + 1
            version = int(version)
            if version == self._weights_version:
                if telemetry._enabled:
                    telemetry.counter(
                        "serving.generation.weight_swap_noops").inc()
                return None
            held = self._param_sets.get(version)
            if held is not None:
                # rollback to a version still pinned by draining sessions:
                # its placed buffers are right there
                placed = held[0]
            else:
                placed = self._place_params(new)
                self._param_sets[version] = (
                    placed, ws.acquire() if ws is not None else None)
            self._params = placed
            self._weights_version = version
            if draft_params and self._draft is not None:
                self._draft.swap_params(draft_params)
            if self._prefix is not None:
                self._prefix.evict_other_versions(version)
            self._gc_param_sets()
        if telemetry._enabled:
            telemetry.counter("serving.generation.weight_swaps").inc()
            telemetry.gauge("serving.generation.weights_version").set(
                version)
        if health._enabled:
            health.event("rollout_swap", engine=self.health_name,
                         version=version,
                         draining=len(self._param_sets) - 1)
        self._logger.info(
            "weights swapped to version %d (%d older version(s) still "
            "draining)", version, len(self._param_sets) - 1)
        return version

    def weights_snapshot(self):
        """Replicated host copy of the CURRENT weights (+ draft) and
        their version — the router pins this before a fleet's first
        rolling swap so automatic rollback always has a target, even
        when the construction params were never published."""
        with self._tick_lock:
            params = {k: np.asarray(v) for k, v in self._params.items()}
            draft = None
            if self._draft is not None and hasattr(self._draft, "_params"):
                draft = {k: np.asarray(v)
                         for k, v in self._draft._params.items()}
            return self._weights_version, params, draft

    def _version_params(self, version):
        """The placed param dict pinned for ``version`` (the cohort
        dispatch in _decode/_spec_decode)."""
        return self._param_sets[version][0]

    def _cohorts(self):
        """Live slots grouped by pinned weights version — one entry in
        steady state; more only while old versions drain after swaps."""
        out = {}
        for slot, sess in enumerate(self._sessions):
            if sess is not None:
                out.setdefault(sess.version, []).append(slot)
        return out

    def _gc_param_sets(self):
        """Release weight versions no live session pins anymore (tick
        lock held). The current version always stays; a released
        version's WeightSet drops its engine reference and the drain is
        journaled — 'both WeightSets stay alive until the old one
        drains' is exactly this refcount."""
        if len(self._param_sets) <= 1:
            return
        pinned = {s.version for s in self._sessions if s is not None}
        pinned.add(self._weights_version)
        for v in [v for v in self._param_sets if v not in pinned]:
            _, ws = self._param_sets.pop(v)
            if ws is not None:
                ws.release()
            if health._enabled:
                health.event("rollout_drained", engine=self.health_name,
                             version=v, current=self._weights_version)
        if telemetry._enabled:
            telemetry.gauge(
                "serving.generation.weight_versions_live").set(
                len(self._param_sets))

    def close(self, timeout=None):
        """Graceful drain: stop admission (``ServerClosedError`` for new
        submits), keep ticking until every admitted AND queued session
        completes, join the worker. Idempotent. Deregisters the health
        probes — a deliberately closed engine must not pin ``/readyz``."""
        self._queue.close()
        self._closed = True
        with self._work:
            self._work.notify_all()
        if self._worker is not None and self._worker.is_alive():
            self._worker.join(timeout)
        health.unregister(self.health_name)
        self._beacon.idle()
        # a closed engine pins no published weights: drop every WeightSet
        # reference (the placed current params stay usable for reopen-free
        # introspection)
        for _, ws in self._param_sets.values():
            if ws is not None:
                ws.release()
        self._param_sets = {self._weights_version: (self._params, None)}

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self):
        out = {"cache": self._cache.snapshot(),
               "buckets": list(self._buckets),
               "slots": self._slots, "live": self._live,
               "queued": len(self._queue),
               "sessions": self.sessions_submitted,
               "max_len": self._max_len,
               "spec_k": self._spec_k,
               "kv_slab_bytes": self.kv_slab_bytes(),
               "weights_version": self._weights_version,
               "weight_versions_live": self.live_weight_versions}
        if self._prefix is not None:
            out["prefix"] = self._prefix.stats()
        if self._draft is not None and hasattr(self._draft, "slab_bytes"):
            out["draft_slab_bytes"] = self._draft.slab_bytes()
        if self._qos is not None:
            out["qos"] = {"park_slots": self._park,
                          "parked": len(self._parked),
                          "weighted_demand": self.qos_demand()}
        return out

    # -- compiled programs ---------------------------------------------------

    def _prefill_fn(self, bucket):
        """The bucket's prefill executable: prompt forward + slab write +
        greedy next token, slab buffers donated."""
        model, cache = self._model, self._cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, ck, cv, toks, length, slot):
                logits, ck, cv = model.prefill(params, ck, cv, toks,
                                               length, slot)
                return jnp.argmax(logits).astype(jnp.int32), ck, cv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("prefill", bucket, self._total_slots, self._slab_len)
        return cache.get_or_build(key, build, persistent=False)

    def _decode_fn(self):
        """THE decode executable — one fused step over the whole slab,
        greedy sampling inside, slab buffers donated. Its key never
        changes, so continuous admission/eviction is hit-only."""
        model, cache = self._model, self._cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, ck, cv, tokens, positions):
                logits, ck, cv = model.decode_step(params, ck, cv, tokens,
                                                   positions)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), ck, cv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("decode", self._total_slots, self._slab_len)
        return cache.get_or_build(key, build, persistent=False)

    def _fork_fn(self):
        """THE prefix-fork executable: copy one slot's slab rows (both K
        and V, all layers) onto another slot, src/dst traced — one
        program serves every (cached entry, session slot) pair. Slab
        donated; a cache hit costs one dispatch plus the suffix prefill."""
        cache = self._cache

        def build():
            import jax
            from jax import lax

            def fn(ck, cv, src, dst):
                rk = lax.dynamic_slice(ck, (src, 0, 0, 0, 0),
                                       (1,) + ck.shape[1:])
                rv = lax.dynamic_slice(cv, (src, 0, 0, 0, 0),
                                       (1,) + cv.shape[1:])
                return (lax.dynamic_update_slice(ck, rk, (dst, 0, 0, 0, 0)),
                        lax.dynamic_update_slice(cv, rv, (dst, 0, 0, 0, 0)))

            return jax.jit(fn, donate_argnums=(0, 1))

        key = ("fork", self._total_slots, self._slab_len)
        return cache.get_or_build(key, build, persistent=False)

    def _suffix_prefill_fn(self, bucket):
        """The bucket's suffix-prefill executable: the prompt tail after
        a fork, writing rows [offset, offset+bucket) and attending the
        forked prefix — offset traced, one program per bucket."""
        model, cache = self._model, self._cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, ck, cv, toks, length, slot, offset):
                logits, ck, cv = model.prefill_at(params, ck, cv, toks,
                                                  length, slot, offset)
                return jnp.argmax(logits).astype(jnp.int32), ck, cv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("suffix_prefill", bucket, self._total_slots, self._slab_len)
        return cache.get_or_build(key, build, persistent=False)

    def _verify_fn(self):
        """THE speculative verify executable — k+1 unrolled decode graphs
        over the whole slab in one program (greedy argmax per position
        inside), slab donated. Like the decode key, it never changes:
        every draft/accept pattern is a hit."""
        model, cache = self._model, self._cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, ck, cv, tokens, positions):
                logits, ck, cv = model.verify_step(params, ck, cv, tokens,
                                                   positions)
                return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                        ck, cv)

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("verify", self._spec_k, self._total_slots, self._slab_len)
        return cache.get_or_build(key, build, persistent=False)

    # -- scheduler -----------------------------------------------------------

    def _has_work(self):
        return (self._live > 0 or len(self._queue) > 0
                or len(self._parked) > 0)

    def _loop(self):
        while True:
            with self._work:
                while not self._closed and not self._has_work():
                    self._work.wait()
                if self._closed and not self._has_work():
                    return
            self._tick_once()

    def _assist_once(self):
        """Caller-runs assist (stream iterators call this while waiting):
        run one tick inline if the tick lock is free. Returns True when a
        tick ran (or there was nothing to do), False when the worker (or
        another assistant) holds the lock — the caller should briefly
        park instead of spinning."""
        if not self._tick_lock.acquire(blocking=False):
            return False
        try:
            if self._has_work():
                self._tick()
            return True
        finally:
            self._tick_lock.release()

    def _tick_once(self):
        with self._tick_lock:
            if self._has_work():
                self._tick()

    def _tick(self):
        """One scheduler tick (tick lock held): sweep deadlines, admit
        prefills into free slots, run ONE fused decode over the slab,
        evict finished sessions. A tick never raises — an executable
        failure fails the live sessions (never-strand, the batcher's
        guard) and reallocates the possibly-donated slab."""
        tele = telemetry._enabled
        obs = observatory._enabled
        decoded = False
        dec_s = None
        t0 = time.perf_counter()
        # the tick's own span tree (admit/decode children via the context
        # var; per-SESSION spans keep their explicit session parents) —
        # observed into tracing.tick_recorder, the generation analog of
        # the slow-step flight recorder (/trace serves it as worst_tick)
        tick_span = tracing.span("generation.tick", cat="generation",
                                 live=self._live, queued=len(self._queue))
        with tick_span:
            try:
                if _staging.overlap_enabled():
                    # overlap order: dispatch the decode FIRST, do the
                    # host bookkeeping (queue expiry, deadline sweep,
                    # admission scan) while the executable runs, THEN
                    # block and commit — the tick's host work hides
                    # behind device time instead of serializing ahead of
                    # it. Sessions evicted or replaced inside that window
                    # are identity-guarded at commit (their tokens are
                    # discarded; the stale slab rows are masked garbage
                    # the next occupant's prefill overwrites). Admitted
                    # prefills chain on the still-lazy decode cache
                    # outputs, so they join the NEXT tick's decode —
                    # per-session token streams stay bit-exact with the
                    # lockstep order below.
                    decoded = self._live > 0
                    t_dec = time.perf_counter()
                    pending = self._decode_dispatch()
                    now = time.monotonic()
                    for req in self._queue.expire(now):
                        self._fail_queued(req.payload, now)
                    for slot, sess in enumerate(self._sessions):
                        if (sess is not None and sess.deadline is not None
                                and now >= sess.deadline):
                            self._evict(
                                slot, "deadline", DeadlineExceededError(
                                    f"session deadline passed after "
                                    f"{sess.generated} generated token(s)"))
                    self._sweep_parked(now)
                    self._admit()
                    if pending is not None:
                        self._decode_commit(pending)
                    # the dispatch→commit window: the swept bookkeeping
                    # rides INSIDE it, so wall − dec_s (the lane's
                    # host_gap_us) is exactly the host work the overlap
                    # order still leaves outside device time
                    dec_s = time.perf_counter() - t_dec
                else:
                    now = time.monotonic()
                    for req in self._queue.expire(now):
                        self._fail_queued(req.payload, now)
                    for slot, sess in enumerate(self._sessions):
                        if (sess is not None and sess.deadline is not None
                                and now >= sess.deadline):
                            self._evict(
                                slot, "deadline", DeadlineExceededError(
                                    f"session deadline passed after "
                                    f"{sess.generated} generated token(s)"))
                    self._sweep_parked(now)
                    self._admit()
                    decoded = self._live > 0
                    t_dec = time.perf_counter()
                    self._decode()
                    dec_s = time.perf_counter() - t_dec
                if len(self._param_sets) > 1:
                    # a swap transition is draining: release versions
                    # whose last session just finished
                    self._gc_param_sets()
            except Exception as e:  # noqa: BLE001 — never-strand + serve on
                self._logger.error("generation tick failed: %r", e)
                tick_span.set(error=repr(e))
                for slot, sess in enumerate(self._sessions):
                    if sess is not None:
                        self._evict(slot, "error", e)
                # parked sessions died with the slab too (their KV rows
                # lived in the same donated buffers) — never-strand
                for park, rec in list(self._parked.items()):
                    self._fail_parked(park, rec, e)
                # the failed executable may have consumed the donated slab
                self._ck, self._cv = self._model.init_cache(
                    self._total_slots, self._slab_len)
                if self._prefix is not None:
                    # the cached rows died with the donated buffers
                    self._prefix.clear("slab_reset")
                if self._draft is not None:
                    self._draft.reset()
                # every session died with the slab: stale weight versions
                # have nothing left to drain for
                self._gc_param_sets()
        if self._has_work():
            # close an assist-vs-worker race: an assist tick pops the
            # queue BEFORE publishing the session as live, and a parked
            # worker re-checking _has_work() inside that window goes back
            # to sleep with nobody left to wake it once the assisting
            # client stops iterating (e.g. takes its first token, then
            # blocks in result()). Any tick that leaves work pending
            # re-notifies, so the worker always resumes the schedule.
            with self._work:
                self._work.notify_all()
        if tracing._enabled:
            tracing.tick_recorder.observe(tick_span.tree())
        if health._enabled:
            # progress beacon: the tick RAN (even a failed one evicted and
            # reallocated — that is progress, not a stall); an empty slab
            # parks the scheduler, so silence while idle is not a stall
            self._beacon.touch()
            if not self._has_work():
                self._beacon.idle()
        if obs and decoded:
            # a decode (or verify) actually swept the slab this tick:
            # the tick wall against THE decode executable's bytes is the
            # per-tick MBU — the honest decode metric (arXiv:2603.09555),
            # bandwidth-bound by construction at steady state
            key = (("verify", self._spec_k, self._total_slots,
                    self._slab_len) if self._spec_k else
                   ("decode", self._total_slots, self._slab_len))
            observatory.observe("generation.tick", self._cache, key,
                                wall_s=time.perf_counter() - t0,
                                exec_s=dec_s)
        if tele:
            dt = time.perf_counter() - t0
            telemetry.counter("serving.generation.ticks").inc()
            telemetry.histogram("serving.generation.tick_us").record(dt * 1e6)
            telemetry.gauge("serving.generation.live_slots").set(self._live)
            now = time.monotonic()
            if not self._has_work():
                # going idle: an un-reset gauge would report the last
                # active window's rate forever (the parked scheduler
                # never recomputes it)
                telemetry.gauge("serving.generation.tokens_per_s").set(0.0)
                self._tokens_window = 0
                self._rate_t0 = now
            elif now - self._rate_t0 >= 0.5:
                telemetry.gauge("serving.generation.tokens_per_s").set(
                    self._tokens_window / (now - self._rate_t0))
                self._tokens_window = 0
                self._rate_t0 = now

    def _free_slots(self):
        """Session slots holding neither a live session nor a cached
        prefix (park-region slots are preemption headroom, never
        admission targets)."""
        held = self._prefix.slots() if self._prefix is not None else ()
        return [i for i in range(self._slots)
                if self._sessions[i] is None and i not in held]

    def _tick_positions(self, active=None):
        """Write positions for the fixed-shape decode/verify executables:
        a live slot's length, and the slab's LAST row for every other
        slot. Dead and — critically — CACHE-HELD slots still get a K/V
        row written every tick (the fixed shape computes all slots); row
        0 would silently corrupt a cached prefix entry's first tokens,
        so the garbage is steered to row ``slab_len - 1``, which no
        entry can own (a cached prompt is at most ``max_len - 1`` tokens
        — submit requires >= 1 generated token — and the speculative
        slab adds scratch rows past that). A verify block's clamped
        writes pile onto the same last row, equally harmless.

        ``active`` (an iterable of slot indices) additionally steers
        every LIVE slot outside it to the same safe row — the per-version
        cohort dispatch during a weight-swap transition: each cohort's
        executable call must advance only its own slots, and a slot only
        ever attends its own rows, so co-resident garbage writes cannot
        perturb another cohort's (bit-exact) output."""
        pos = self._lengths.copy()
        safe = self._slab_len - 1
        act = None if active is None else set(active)
        for i, s in enumerate(self._sessions):
            if s is None or (act is not None and i not in act):
                pos[i] = safe
        return pos

    def _prefix_claimable(self):
        """Cache entries session pressure may evict: everything above the
        retention floor. The floor (one entry, zero on a single-slot
        engine) keeps the hottest prefix alive through full occupancy —
        without it a saturated slab would evict the shared system prompt
        and every later admission would cold-miss, exactly the fleet
        pathology the cache exists to prevent."""
        if self._prefix is None:
            return 0
        keep = min(1, max(self._slots - 1, 0))
        return max(len(self._prefix) - keep, 0)

    def _claim_slot(self, free):
        """Pop a slot for a session: from the free list, else by evicting
        the LRU refcount-zero prefix entry above the retention floor —
        live sessions outrank cached prefixes. None when the slab is
        truly full."""
        if free:
            return free.pop(0)
        if self._prefix_claimable() and len(self._queue):
            return self._prefix.evict_lru("slot_pressure")
        return None

    def _admit(self):
        """Move queued sessions into free slots (prefill), oldest first
        (QoS active: class/deadline order), until the slab is full, the
        queue is empty, or the tick budget is spent — at least one
        admission per tick when a slot is free (or freeable by evicting
        a cached prefix), so backlog always drains even under a tiny
        budget. Under QoS, a full slab with a higher-class request at
        the queue head first PARKS the youngest batch session (one per
        tick — bounded churn) to free its slot."""
        free = self._free_slots()
        if self._qos is not None and not free:
            freed = self._preempt_for_priority()
            if freed is not None:
                free = [freed]
        if not free and not (self._prefix_claimable()
                             and len(self._queue)):
            return
        t0 = time.perf_counter()
        tele = telemetry._enabled
        with tracing.span("generation.admit", cat="generation",
                          free=len(free)):
            self._admit_into(free, t0, tele)

    def _admit_into(self, free, t0, tele):
        import jax.numpy as jnp

        while True:
            slot = self._claim_slot(free)
            if slot is None:
                return
            if (self._qos is not None and self._parked
                    and self._should_resume()):
                # no queued request outranks the parked batch work: un-park
                # the oldest preempted session into this slot instead of
                # admitting (anti-starvation — parked work drains the
                # moment pressure lifts)
                if self._resume_into(slot):
                    if time.perf_counter() - t0 > self._tick_budget_s:
                        return
                    continue
            batch, _ = self._queue.get_batch_nowait(1)
            if not batch:
                free.append(slot)
                return
            sess = batch[0].payload
            sess.qos_rank = batch[0].qos_rank
            now = time.monotonic()
            if sess.deadline is not None and now >= sess.deadline:
                self._fail_queued(sess, now)
                free.append(slot)
                continue
            n = int(sess.prompt.size)
            # prefix-cache lane: fork the longest usable cached prefix
            # slot-to-slot, then prefill only the unmatched suffix
            node = None
            if self._prefix is not None:
                node, m = self._prefix.match(
                    sess.prompt, version=self._weights_version)
                if node is None or m < self._prefix_min:
                    node = None
                elif m + self.bucket_for(n - m) > self._slab_len:
                    # the suffix BUCKET (not just the suffix) must fit
                    # past the split point — dynamic_update_slice CLAMPS
                    # an overhanging block start, which would smear the
                    # padded suffix over the forked prefix rows. Near-
                    # capacity prompts fall back to the always-in-bounds
                    # full prefill instead
                    node = None
            t_pf = time.perf_counter()
            trc = tracing._enabled and sess.span is not None
            if trc:
                # queue-wait child reconstructed from the submit instant
                tracing.emit_span("generation.queued", sess.span.t0,
                                  tracing.now_us() - sess.span.t0,
                                  cat="generation", parent=sess.span)
                t_pf_us = tracing.now_us()
            try:
                if node is not None:
                    tok = self._fork_admit(sess, slot, node, m)
                else:
                    bucket = self.bucket_for(n)
                    padded = np.zeros(bucket, np.int32)
                    padded[:n] = sess.prompt
                    fn = self._prefill_fn(bucket)
                    tok, self._ck, self._cv = fn(
                        self._params, self._ck, self._cv,
                        jnp.asarray(padded), jnp.asarray(n, jnp.int32),
                        jnp.asarray(slot, jnp.int32))
                    tok = int(tok)
                    if tele and self._prefix is not None:
                        telemetry.counter(
                            "serving.generation.prefix.misses").inc()
            except Exception as e:
                # the popped session is in neither the queue nor a slot —
                # the tick handler only evicts ADMITTED sessions, so fail
                # its stream here or it is stranded forever (never-strand,
                # the batcher's guard); re-raise for the slab reallocation
                if tele:
                    telemetry.counter("serving.generation.evictions").inc()
                    telemetry.counter("serving.generation.evict_error").inc()
                sess.stream._fail(e)
                if sess.span is not None:
                    sess.span.set(error=repr(e), reason="error").finish()
                raise
            if trc:
                tracing.emit_span("generation.prefill", t_pf_us,
                                  tracing.now_us() - t_pf_us,
                                  cat="generation", parent=sess.span,
                                  bucket=self.bucket_for(n - sess.prefix_len),
                                  slot=slot, cached_prefix=sess.prefix_len)
            sess.slot = slot
            # pinned for the session's whole life: after a swap the tick
            # keeps decoding this session under these exact weights
            sess.version = self._weights_version
            self._admit_seq += 1
            sess.admit_seq = self._admit_seq
            self._sessions[slot] = sess
            self._lengths[slot] = n
            self._last_tok[slot] = tok
            self._live += 1
            if self._draft is not None:
                self._draft.on_admit(slot, sess.prompt, tok)
            self._deliver(sess, tok, first=True)
            if tele:
                telemetry.counter("serving.generation.prefills").inc()
                telemetry.histogram("serving.generation.prefill_us").record(
                    (time.perf_counter() - t_pf) * 1e6)
            # cache the full prompt's KV for future sessions while a free
            # slot exists (never evict FOR an insert: only live sessions
            # force evictions) — the slot's rows [0, n) are exactly the
            # prompt's K/V right after prefill, so one fork snapshots them
            if (self._prefix is not None and n >= self._prefix_min
                    and free):
                cslot = free[0]
                if self._prefix.insert(sess.prompt, cslot,
                                       version=sess.version) is not None:
                    free.pop(0)
                    fn = self._fork_fn()
                    self._ck, self._cv = fn(
                        self._ck, self._cv, jnp.asarray(slot, jnp.int32),
                        jnp.asarray(cslot, jnp.int32))
            # the prompt's last token may already end the session; a slot
            # freed that way goes straight back on the free list so a
            # burst of first-token-EOS sessions drains within the tick
            self._maybe_finish(slot)
            if self._sessions[slot] is None:
                free.append(slot)
            if time.perf_counter() - t0 > self._tick_budget_s:
                return

    def _fork_admit(self, sess, slot, node, m):
        """Cache-hit admission: pin the entry, fork its slot onto the
        session's, suffix-prefill the unmatched tail at offset ``m``.
        Returns the first sampled token."""
        import jax.numpy as jnp

        suffix = sess.prompt[m:]
        ns = int(suffix.size)
        bucket = self.bucket_for(ns)
        padded = np.zeros(bucket, np.int32)
        padded[:ns] = suffix
        self._prefix.acquire(node)
        try:
            fk = self._fork_fn()
            self._ck, self._cv = fk(self._ck, self._cv,
                                    jnp.asarray(node.slot, jnp.int32),
                                    jnp.asarray(slot, jnp.int32))
            fn = self._suffix_prefill_fn(bucket)
            tok, self._ck, self._cv = fn(
                self._params, self._ck, self._cv, jnp.asarray(padded),
                jnp.asarray(ns, jnp.int32), jnp.asarray(slot, jnp.int32),
                jnp.asarray(m, jnp.int32))
        finally:
            self._prefix.release(node)
        sess.prefix_len = m
        sess.stream.cached_prefix_len = m
        if telemetry._enabled:
            telemetry.counter("serving.generation.prefix.hits").inc()
            telemetry.counter("serving.generation.prefix.forks").inc()
            telemetry.counter(
                "serving.generation.prefix.cached_tokens_served").inc(m)
        return int(tok)

    def _decode(self):
        """ONE fused step over the whole slab; every live session
        advances one token (plain) or up to ``spec_k + 1`` (speculative
        verify). Dead slots ride along as masked garbage — that fixed
        shape is exactly what makes mid-stream admit/evict free.

        During a weight-swap transition (live sessions pinned to more
        than one version) the SAME executable runs once per version
        cohort with that cohort's pinned params, other cohorts' slots
        steered to the safe row — N dispatches, zero new programs, and
        every session's output stays bit-exact with an unswapped engine
        on its own weights.

        Split into :meth:`_decode_dispatch` (launch the executables,
        tokens still lazy) and :meth:`_decode_commit` (block + deliver)
        so the overlap tick can do its host bookkeeping between the two;
        this method is the back-to-back composition."""
        pending = self._decode_dispatch()
        if pending is not None:
            self._decode_commit(pending)

    def _decode_dispatch(self):
        """Dispatch the decode (or verify) executable once per version
        cohort WITHOUT materializing the token output. Cohort dispatch
        order and inputs are identical to the fused path: a later
        cohort's call only reads the earlier ones' cache outputs (pure
        lazy dataflow) and every non-member slot is steered to the safe
        row, so committing before or after the remaining dispatches is
        bit-equivalent. Returns the pending state for
        :meth:`_decode_commit`, or None when no slot is live."""
        import jax.numpy as jnp

        if self._live == 0:
            return None
        if self._spec_k:
            return self._spec_dispatch()
        fn = self._decode_fn()
        cohorts = self._cohorts()
        mixed = len(cohorts) > 1
        pending = []
        for version in sorted(cohorts):
            slots = cohorts[version]
            with tracing.span("generation.decode", cat="generation",
                              live=len(slots), version=version):
                toks, self._ck, self._cv = fn(
                    self._version_params(version), self._ck, self._cv,
                    jnp.asarray(self._last_tok),
                    jnp.asarray(self._tick_positions(
                        slots if mixed else None)))
            # snapshot the cohort's sessions: a slot evicted or re-
            # admitted between dispatch and commit fails the identity
            # check and its token is discarded
            pending.append((slots, [self._sessions[s] for s in slots],
                            toks))
        return ("plain", pending)

    def _decode_commit(self, state):
        """Block on the dispatched token outputs and commit them:
        deliver one token per still-live slot, advance lengths, evict
        terminal sessions. A slot whose session changed since dispatch
        (overlap-window evict/re-admit) is skipped — its slab write is
        masked garbage the next prefill overwrites."""
        kind, pending = state
        if kind == "spec":
            self._spec_commit(pending)
            return
        trc = tracing._enabled
        live = 0
        for slots, snap, toks in pending:
            toks = np.asarray(toks)
            if trc:
                t_us = tracing.now_us()
            for slot, dispatched in zip(slots, snap):
                sess = self._sessions[slot]
                if sess is None or sess is not dispatched:
                    continue
                live += 1
                # the token we fed now occupies position lengths[slot]
                self._lengths[slot] += 1
                tok = int(toks[slot])
                self._last_tok[slot] = tok
                if trc and sess.span is not None:
                    tracing.emit_span("generation.decode_tick", t_us, 0.0,
                                      cat="generation", parent=sess.span,
                                      position=int(self._lengths[slot]))
                self._deliver(sess, tok)
                self._maybe_finish(slot)
            if telemetry._enabled:
                telemetry.counter("serving.generation.tick_slots").inc(
                    self._slots)
        if telemetry._enabled:
            telemetry.counter("serving.generation.decode_tokens").inc(live)

    def _spec_dispatch(self):
        """Speculative half of :meth:`_decode_dispatch`: draft proposes,
        the verify executable is dispatched per cohort, tokens stay
        lazy. Returns the pending state for :meth:`_spec_commit`."""
        import jax.numpy as jnp

        k = self._spec_k
        # the draft proposes ONCE for all slots with its current (post-
        # swap) params — proposals are free to be "wrong" for an old-
        # version cohort, its own verify corrects them bit-exactly; a
        # bad acceptance ratio during the drain is the whole cost
        props = np.asarray(
            self._draft.propose(k, self._sessions), np.int32)   # [S, k]
        tokens = np.concatenate([self._last_tok[:, None], props], axis=1)
        fn = self._verify_fn()
        cohorts = self._cohorts()
        mixed = len(cohorts) > 1
        pending = []
        for version in sorted(cohorts):
            slots = cohorts[version]
            with tracing.span("generation.verify", cat="generation",
                              live=len(slots), k=k, version=version):
                toks, self._ck, self._cv = fn(
                    self._version_params(version), self._ck, self._cv,
                    jnp.asarray(tokens),
                    jnp.asarray(self._tick_positions(
                        slots if mixed else None)))
            pending.append((slots, [self._sessions[s] for s in slots],
                            toks))
        return ("spec", (props, pending))

    def _spec_commit(self, state):
        """Block on the dispatched verify outputs and commit: each
        still-live slot takes the longest agreeing draft prefix plus the
        target's next token (1..k+1 tokens), rolling the rest back by
        NOT advancing its position past the last commit — the rejected
        rows beyond the new frontier are never attended and the next
        tick overwrites them in order before they could be."""
        props, pending = state
        k = self._spec_k
        tele = telemetry._enabled
        trc = tracing._enabled
        live = accepted = committed_total = 0
        for slots, snap, toks in pending:
            toks = np.asarray(toks)                             # [S, k+1]
            if trc:
                t_us = tracing.now_us()
            for slot, dispatched in zip(slots, snap):
                sess = self._sessions[slot]
                if sess is None or sess is not dispatched:
                    continue
                live += 1
                t = toks[slot]
                d = props[slot]
                a = 0
                while a < k and d[a] == t[a]:
                    a += 1
                committed = []
                for j in range(a + 1):
                    # same bookkeeping as one plain decode step: the token
                    # we fed at position lengths[slot] is now in the slab,
                    # t[j] is the sampled-but-not-yet-fed continuation
                    self._lengths[slot] += 1
                    tok = int(t[j])
                    self._last_tok[slot] = tok
                    committed.append(tok)
                    self._deliver(sess, tok)
                    self._maybe_finish(slot)
                    if self._sessions[slot] is None:
                        break
                if trc and sess.span is not None:
                    tracing.emit_span("generation.decode_tick", t_us, 0.0,
                                      cat="generation", parent=sess.span,
                                      position=int(self._lengths[slot]),
                                      committed=len(committed), accepted=a)
                if (self._sessions[slot] is not None
                        and self._draft is not None):
                    self._draft.on_commit(slot, committed)
                # accepted = draft proposals that actually became committed
                # tokens. On a full commit that is `a` (the bonus token is
                # not a draft); when the loop broke early on a terminal
                # state every committed token so far WAS a matching draft —
                # counting the unreachable tail of `a` would inflate the
                # acceptance_ratio operators tune k against
                accepted += min(len(committed), a)
                committed_total += len(committed)
            if tele:
                telemetry.counter("serving.generation.tick_slots").inc(
                    self._slots)
        if tele:
            telemetry.counter("serving.generation.decode_tokens").inc(live)
            telemetry.counter("serving.generation.spec.ticks").inc()
            telemetry.counter("serving.generation.spec.verified_slots").inc(
                live)
            telemetry.counter("serving.generation.spec.proposed").inc(
                live * k)
            telemetry.counter("serving.generation.spec.accepted").inc(
                accepted)
            telemetry.counter("serving.generation.spec.rolled_back").inc(
                live * k - accepted)
            telemetry.counter("serving.generation.spec.committed").inc(
                committed_total)

    # -- delivery / eviction -------------------------------------------------

    def _deliver(self, sess, tok, first=False):
        sess.generated += 1
        sess.stream._push(tok)
        self._tokens_window += 1
        if self._qos is not None:
            # token-rate quota burn-down — may push the tenant's bucket
            # negative, which blocks its NEXT admission (generation length
            # is unknowable at admit time, so charging at delivery is the
            # only honest accounting)
            self._qos.charge_tokens(sess.tenant, 1)
        if telemetry._enabled:
            telemetry.counter("serving.generation.tokens").inc()
            spec = (self._qos.spec_for(sess.tenant)
                    if self._qos is not None else None)
            if spec is not None:
                telemetry.counter(
                    qos.labeled_metric("qos.tokens", spec)).inc()
            # generated == 1 guards the adopt path: a migrated session's
            # re-prefill redelivers into an old stream whose TTFT already
            # happened on the source replica — recording it again would
            # double-count (and flatter: the adopting engine only re-ran
            # the prefill, not the queue wait)
            if first and sess.generated == 1:
                ttft = (time.monotonic() - sess.stream.submitted_at) * 1e6
                telemetry.histogram("serving.generation.ttft_us").record(
                    ttft)
                if spec is not None:
                    # the per-tenant histogram the SLO burn rows
                    # (qos.attach_slo) and the worst-tenant report line read
                    telemetry.histogram(
                        qos.labeled_metric("qos.ttft_us", spec)).record(ttft)
                if sess.prefix_len:
                    # hit-path TTFT separately: the fork+suffix admission
                    # vs the full-prefill population above
                    telemetry.histogram(
                        "serving.generation.prefix.ttft_us").record(ttft)

    def _maybe_finish(self, slot):
        """Evict the slot if its session just reached a terminal state."""
        sess = self._sessions[slot]
        if sess.eos_id is not None and self._last_tok[slot] == sess.eos_id:
            self._evict(slot, "eos")
        elif sess.generated >= sess.max_new_tokens:
            self._evict(slot, "finished")
        elif self._lengths[slot] + 1 > self._max_len:
            # no room to write the next token's K/V — the slab, not the
            # request, is the binding constraint here
            self._evict(slot, "max_len")

    def _evict(self, slot, reason, exc=None):
        """Free the slot: host metadata only — the KV rows stay as masked
        garbage until the next occupant's prefill rewrites them."""
        sess = self._sessions[slot]
        self._sessions[slot] = None
        self._lengths[slot] = 0
        self._last_tok[slot] = 0
        self._live -= 1
        if self._draft is not None:
            self._draft.on_evict(slot)
        if telemetry._enabled:
            telemetry.counter("serving.generation.evictions").inc()
            telemetry.counter(f"serving.generation.evict_{reason}").inc()
        if health._enabled and reason not in ("eos", "finished"):
            # journal only the ABNORMAL evictions (deadline/max_len/error)
            # — normal completions would drown the ring
            health.event("generation_evict", engine=self.health_name,
                         slot=slot, reason=reason,
                         tokens=sess.generated)
        if exc is not None:
            sess.stream._fail(exc)
        else:
            sess.stream._finish()
        if sess.span is not None:
            t_us = tracing.now_us()
            tracing.emit_span("generation.evict", t_us, 0.0,
                              cat="generation", parent=sess.span,
                              reason=reason)
            sess.span.set(reason=reason, tokens=sess.generated,
                          **({"error": repr(exc)} if exc is not None else {}))
            sess.span.finish()

    def _fail_queued(self, sess, now):
        """Deadline death while still queued: no slot to free, just the
        stream to unblock (and the span tree to close)."""
        exc = DeadlineExceededError(
            f"session waited {now - sess.stream.submitted_at:.3f}s in "
            "queue, past its deadline")
        if telemetry._enabled:
            telemetry.counter("serving.generation.evict_deadline").inc()
            telemetry.counter("serving.generation.evictions").inc()
        if health._enabled:
            health.event("generation_evict", engine=self.health_name,
                         reason="deadline", queued=True)
        sess.stream._fail(exc)
        if sess.span is not None:
            sess.span.set(error=repr(exc), reason="deadline").finish()

    # -- QoS park region (preemption / resume / migration) -------------------

    def _sweep_parked(self, now):
        """Deadline sweep over the park region — parking a session does
        not stop its clock (the client's deadline is wall time, and a
        parked batch session under sustained interactive pressure may
        never get its slot back)."""
        if not self._parked:
            return
        for park, rec in list(self._parked.items()):
            sess = rec["sess"]
            if sess.deadline is not None and now >= sess.deadline:
                self._fail_parked(
                    park, rec, DeadlineExceededError(
                        f"session deadline passed while parked after "
                        f"{sess.generated} generated token(s)"),
                    reason="deadline")

    def _fail_parked(self, park, rec, exc, reason="error"):
        """Terminal failure for a PARKED session: free the park slot and
        fail the stream in-band (never-strand — a parked session is in
        neither the queue nor a live slot, so nobody else will)."""
        del self._parked[park]
        self._park_free.append(park)
        sess = rec["sess"]
        if telemetry._enabled:
            telemetry.counter("serving.generation.evictions").inc()
            telemetry.counter(f"serving.generation.evict_{reason}").inc()
        if health._enabled:
            health.event("generation_evict", engine=self.health_name,
                         reason=reason, parked=True, tokens=sess.generated)
        sess.stream._fail(exc)
        if sess.span is not None:
            sess.span.set(error=repr(exc), reason=reason,
                          parked=True).finish()

    def _preempt_for_priority(self):
        """Park the YOUNGEST live batch-class session (fewest sunk tokens
        by admission order) when a higher-class request heads the queue
        and the slab is full: one traced fork copies its KV rows into a
        free park slot, host metadata moves aside, and the slot frees for
        the interactive admission. One victim per call (the tick calls
        once) bounds preemption churn. Returns the freed slot, or None
        when preemption is impossible (no park headroom, no batch victim)
        or unwarranted (the queue head is itself batch — an AGED batch
        request never preempts, aging only reorders the queue).

        Zero new executables: the fork program is the prefix cache's /
        warm()'s, keyed ``("fork", total_slots, slab_len)``."""
        import jax.numpy as jnp

        if not self._park_free:
            return None
        head = self._queue.peek()
        if (head is None or head.qos_rank is None
                or head.qos_rank >= qos.BATCH_RANK):
            return None
        victim = None
        for slot in range(self._slots):
            sess = self._sessions[slot]
            if sess is None or sess.qos_rank != qos.BATCH_RANK:
                continue
            if (victim is None
                    or sess.admit_seq > self._sessions[victim].admit_seq):
                victim = slot
        if victim is None:
            return None
        sess = self._sessions[victim]
        park = self._park_free.pop()
        try:
            fn = self._fork_fn()
            self._ck, self._cv = fn(self._ck, self._cv,
                                    jnp.asarray(victim, jnp.int32),
                                    jnp.asarray(park, jnp.int32))
        except Exception:
            # the victim is still live in its slot; the tick handler's
            # sweep will fail it with everyone else
            self._park_free.append(park)
            raise
        self._parked[park] = {"sess": sess,
                              "length": int(self._lengths[victim]),
                              "last_tok": int(self._last_tok[victim]),
                              "parked_at": time.monotonic()}
        # host metadata moves aside WITHOUT failing the stream — the
        # session is paused, not dead; its slot row becomes masked
        # garbage steered to the safe row by _tick_positions
        self._sessions[victim] = None
        self._lengths[victim] = 0
        self._last_tok[victim] = 0
        self._live -= 1
        if self._draft is not None:
            self._draft.on_evict(victim)
        spec = self._qos.spec_for(sess.tenant)
        if telemetry._enabled:
            telemetry.counter("serving.generation.preemptions").inc()
            telemetry.counter(qos.labeled_metric("qos.preempted", spec)).inc()
        if health._enabled:
            health.event("qos_preempt", engine=self.health_name,
                         slot=victim, park=park, tenant=spec.name,
                         tokens=sess.generated)
        if sess.span is not None:
            tracing.emit_span("generation.preempt", tracing.now_us(), 0.0,
                              cat="generation", parent=sess.span,
                              slot=victim, park=park)
        return victim

    def _should_resume(self):
        """A free slot goes to a parked session unless a HIGHER-class
        request heads the queue (batch-vs-batch: the parked session wins
        — it has sunk prefill + decode work the queued one hasn't)."""
        head = self._queue.peek()
        return (head is None or head.qos_rank is None
                or head.qos_rank >= qos.BATCH_RANK)

    def _resume_into(self, slot):
        """Un-park the OLDEST parked session into the free slot: one
        traced fork copies its KV rows back, host metadata is restored,
        and greedy decode continues bit-exact with an uninterrupted run
        (fork is a bitwise row copy; decode is slot-index-independent).
        Returns True when a session was resumed."""
        import jax.numpy as jnp

        park = min(self._parked,
                   key=lambda p: self._parked[p]["parked_at"])
        rec = self._parked.pop(park)
        sess = rec["sess"]
        try:
            fn = self._fork_fn()
            self._ck, self._cv = fn(self._ck, self._cv,
                                    jnp.asarray(park, jnp.int32),
                                    jnp.asarray(slot, jnp.int32))
        except Exception as e:
            # never-strand: the session is now in neither _parked nor a
            # slot — fail its stream here, then let the tick handler
            # reallocate the slab
            self._park_free.append(park)
            sess.stream._fail(e)
            if sess.span is not None:
                sess.span.set(error=repr(e), reason="error").finish()
            raise
        self._park_free.append(park)
        sess.slot = slot
        self._sessions[slot] = sess
        self._lengths[slot] = rec["length"]
        self._last_tok[slot] = rec["last_tok"]
        self._live += 1
        if self._draft is not None:
            # rebuild the draft's context: prompt + all delivered tokens
            # except the pending last (exactly what on_admit saw at the
            # original admission, extended by the generated prefix)
            ctx = np.concatenate([
                sess.prompt,
                np.asarray(sess.stream.tokens[:-1], np.int32)])
            self._draft.on_admit(slot, ctx, rec["last_tok"])
        spec = self._qos.spec_for(sess.tenant)
        if telemetry._enabled:
            telemetry.counter(qos.labeled_metric("qos.resumed", spec)).inc()
        if health._enabled:
            health.event("qos_resume", engine=self.health_name, slot=slot,
                         tenant=spec.name,
                         parked_s=round(
                             time.monotonic() - rec["parked_at"], 3))
        if sess.span is not None:
            tracing.emit_span("generation.resume", tracing.now_us(), 0.0,
                              cat="generation", parent=sess.span, slot=slot,
                              park=park)
        return True

    def qos_demand(self):
        """Fairness-weighted demand for the autoscaler: every live and
        parked session plus every queued request, each weighted by its
        tenant's QoS weight (interactive work votes harder for replicas
        than batch). None while QoS is off — callers fall back to the
        raw ``live_slots + queue_depth`` count."""
        if self._qos is None:
            return None
        d = 0.0
        for sess in self._sessions:
            if sess is not None:
                d += self._qos.weight(sess.tenant)
        for rec in self._parked.values():
            d += self._qos.weight(rec["sess"].tenant)
        return d + self._queue.weighted_depth()

    def eject_parked(self, max_n=None):
        """Pop up to ``max_n`` parked sessions (oldest first) OUT of this
        engine as host-side migration records — the router's
        ``rebalance_parked`` hands them to a less-loaded peer replica's
        :meth:`adopt`. Each record carries everything needed to continue
        the generation elsewhere: prompt, tokens generated so far,
        remaining budget, tenant, and the LIVE stream (the client keeps
        iterating the same object; only its engine changes). The park
        slots free immediately — the KV rows become masked garbage."""
        out = []
        with self._tick_lock:
            parks = sorted(self._parked,
                           key=lambda p: self._parked[p]["parked_at"])
            if max_n is not None:
                parks = parks[:max_n]
            for park in parks:
                rec = self._parked.pop(park)
                self._park_free.append(park)
                sess = rec["sess"]
                out.append({"prompt": sess.prompt,
                            "tokens": list(sess.stream.tokens),
                            "max_new_tokens": sess.max_new_tokens,
                            "eos_id": sess.eos_id,
                            "deadline": sess.deadline,
                            "tenant": sess.tenant,
                            "stream": sess.stream,
                            "span": sess.span})
        if out and telemetry._enabled:
            telemetry.counter("serving.generation.qos.ejected").inc(len(out))
        return out

    def adopt(self, record):
        """Admit a migrated session ejected from a peer replica:
        re-prefill the FULL context (prompt + every token generated so
        far) through the normal admission path and keep delivering the
        remaining budget into the ORIGINAL stream. Greedy continuation
        is bit-exact with a fresh submit of that context — it IS one
        (same prefill executable, same greedy argmax). The request rides
        ``qos_exempt`` (its quota was charged at original admission;
        double-charging would punish the tenant for the fleet's
        rebalancing). Returns False when the context cannot fit this
        engine (caller keeps the record and tries elsewhere)."""
        toks = [int(t) for t in record["tokens"]]
        ctx = np.concatenate([np.asarray(record["prompt"], np.int32).ravel(),
                              np.asarray(toks, np.int32)])
        n = int(ctx.size)
        remaining = int(record["max_new_tokens"]) - len(toks)
        if (remaining < 1 or n > self._buckets[-1]
                or n + remaining > self._max_len or self._closed):
            return False
        stream = record["stream"]
        sess = _Session(ctx, record["max_new_tokens"], record["eos_id"],
                        record["deadline"], stream,
                        tenant=record["tenant"])
        sess.generated = len(toks)
        sess.span = record.get("span")
        # the stream's caller-runs assist must drive THIS engine's ticks
        # from now on
        stream._engine = self
        req = Request([ctx], 1, stream._future, deadline=record["deadline"],
                      payload=sess, tenant=record["tenant"])
        req.qos_exempt = True
        try:
            self._queue.put(req)
        except Exception:
            return False
        if telemetry._enabled:
            telemetry.counter("serving.generation.qos.adopted").inc()
        if health._enabled:
            self._beacon.arm()
        with self._work:
            self.sessions_submitted += 1
            self._work.notify_all()
        return True
