"""GenerationEngine — token-level continuous batching over a KV slot slab.

PR 5's :class:`~mxnet_tpu.serving.batcher.DynamicBatcher` schedules at
REQUEST granularity: a batch forms, computes once, and every member leaves
together. Autoregressive generation breaks that shape — sessions are
hundreds of sequential single-token steps of wildly different counts, so
request-level batching would hold every finished sequence hostage to the
longest one (and re-running the full forward per token would cost O(T) per
token, O(T²) per sequence). This engine is the token-level scheduler:

* **slot-based session store** — a preallocated KV slab
  ``[max_slots, layers, heads, max_len, head_dim]``
  (:meth:`TransformerLM.init_cache`) whose shape NEVER changes: admitting
  a session is a prefill write into a free slot index, evicting is
  clearing host-side metadata — continuous batching without a recompile,
  ever (the arXiv:2603.09555 compile-once O(1)-cache discipline).
* **continuous scheduling** — every engine tick runs ONE fused
  ``decode_step`` over the whole slab (all live sessions advance one
  token together), evicts finished/EOS/deadline-expired sessions, and
  admits queued prefills into the freed slots mid-stream. The intake is
  PR 5's :class:`~mxnet_tpu.serving.admission.AdmissionQueue`
  (``QueueFullError`` backpressure, ``ServerClosedError`` after close,
  per-session deadlines swept per tick via ``expire()``), prompts pad up
  a prefill-length bucket ladder, and a blocking stream iterator assists
  caller-runs style.
* **compile discipline** — one ``CompileCache("generation")`` entry per
  prefill bucket plus exactly ONE decode executable, all with the slab
  buffers donated (``persistent=False``: donated programs stay out of the
  on-disk XLA cache, the PR 3 aliasing rule). ``serving.warmup`` pins the
  exact count ahead of traffic; steady state compiles nothing.

Telemetry rides ``serving.generation.*`` (live-slot gauge, tokens/s,
TTFT/tick histograms, per-reason eviction counters, derived
``slot_fill_ratio``); tracing builds one span tree per session (root →
queued → prefill → decode ticks → evict); the slab registers under the
``kv_cache`` memory-census category.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ... import health
from ... import memory
from ... import telemetry
from ... import tracing
from ...base import MXNetError, getenv, register_env
from ...compile_cache import CompileCache
from ...log import get_logger
from ..admission import AdmissionQueue, DeadlineExceededError, Request
from ..health import attach_engine, queue_ready
from .session import GenerationStream

__all__ = ["GenerationEngine", "prefill_ladder"]

register_env("MXNET_GENERATION_SLOTS", 8,
             "KV-slab slot count per generation engine: the max number of "
             "concurrently-decoding sessions (one fused decode_step covers "
             "the whole slab each tick)")
register_env("MXNET_GENERATION_MAX_LEN", 256,
             "KV-slab sequence capacity per slot (prompt + generated "
             "tokens); bounds per-slot HBM at "
             "2*layers*heads*max_len*head_dim*dtype bytes")
register_env("MXNET_GENERATION_PREFILL_BUCKETS", "",
             "prefill-length bucket ladder (comma-separated ints, each a "
             "compiled prefill program); empty = powers of two from 8 up "
             "to MXNET_GENERATION_MAX_LEN")
register_env("MXNET_GENERATION_TICK_BUDGET_MS", 10.0,
             "max milliseconds one scheduler tick spends admitting queued "
             "prefills before the fused decode runs again (>= 1 admission "
             "per tick when slots are free, so queues always drain)")


def prefill_ladder(buckets, max_len):
    """Normalize a prefill bucket spec (None ->
    ``MXNET_GENERATION_PREFILL_BUCKETS``; empty -> powers of two up to
    ``max_len``) into an ascending tuple capped at ``max_len`` —
    spec parsing/validation shared with the predictor's
    :func:`~mxnet_tpu.serving.predictor.bucket_ladder`."""
    from ..predictor import bucket_ladder

    if buckets is None:
        buckets = getenv("MXNET_GENERATION_PREFILL_BUCKETS")
    if not (buckets.strip() if isinstance(buckets, str) else buckets):
        b, buckets = 8, []
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    out = bucket_ladder(buckets, env_var="MXNET_GENERATION_PREFILL_BUCKETS")
    return tuple(sorted({min(int(b), int(max_len)) for b in out}))


class _Session:
    """Engine-side state of one admitted (or queued) generation."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "deadline", "stream",
                 "span", "slot", "generated")

    def __init__(self, prompt, max_new_tokens, eos_id, deadline, stream):
        self.prompt = prompt            # np.int32 [n]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.deadline = deadline
        self.stream = stream
        self.span = None                # tracing root (MXNET_TRACING=1)
        self.slot = None
        self.generated = 0


class GenerationEngine:
    """Continuous-batching autoregressive server over one model replica.

    Parameters
    ----------
    model : TransformerLM
        Functional model providing ``init_cache`` / ``prefill`` /
        ``decode_step`` (pure, jit-able, cache-donating).
    params : dict[str, jax.Array]
        The model's parameters (``init_params`` placement).
    max_slots / max_len / buckets / tick_budget_ms :
        Overrides of the ``MXNET_GENERATION_*`` knobs.
    max_queue : int, optional
        Intake bound (default ``MXNET_SERVING_MAX_QUEUE``).
    eos_id : int, optional
        Default end-of-sequence token for sessions that don't pass one.
    start : bool
        Spin the scheduler worker thread (tests drive ticks manually with
        ``False``).
    """

    def __init__(self, model, params, max_slots=None, max_len=None,
                 buckets=None, max_queue=None, tick_budget_ms=None,
                 eos_id=None, start=True):
        self._model = model
        self._params = params
        self._slots = int(getenv("MXNET_GENERATION_SLOTS")
                          if max_slots is None else max_slots)
        self._max_len = int(getenv("MXNET_GENERATION_MAX_LEN")
                            if max_len is None else max_len)
        self._max_len = min(self._max_len, model.cfg.max_len)
        if self._slots < 1:
            raise MXNetError(f"need >= 1 slot, got {self._slots}")
        self._buckets = prefill_ladder(buckets, self._max_len)
        budget_ms = (getenv("MXNET_GENERATION_TICK_BUDGET_MS")
                     if tick_budget_ms is None else tick_budget_ms)
        self._tick_budget_s = float(budget_ms) / 1e3
        self._eos_id = eos_id
        self._logger = get_logger("mxnet_tpu.serving.generation")

        self._cache = CompileCache("generation")
        self._ck, self._cv = model.init_cache(self._slots, self._max_len)
        # host-side slot metadata — only the tick loop (under _tick_lock)
        # mutates these
        self._sessions = [None] * self._slots
        self._lengths = np.zeros(self._slots, np.int32)
        self._last_tok = np.zeros(self._slots, np.int32)
        self._live = 0

        self._queue = AdmissionQueue(max_queue,
                                     metric_prefix="serving.generation")
        self._tick_lock = threading.Lock()
        self._work = threading.Condition()
        self._closed = False
        self._tokens_window = 0
        self._rate_t0 = time.monotonic()
        self.sessions_submitted = 0   # per-replica intake (router balance)
        # fleet-health wiring: liveness/readiness probes (/healthz,
        # /readyz, router drain) + the scheduler-tick progress beacon the
        # stall watchdog monitors. Registration is construction-time;
        # the tick path pays one health._enabled read when the layer is
        # off (pinned by test_health.py)
        self._warmed = False          # set by warm(); ready() also
        #                               accepts traffic-compiled engines
        self.health_name, self._beacon = attach_engine(self)

        # the slab is device state the engine REPLACES every tick, so the
        # census needs a live view, not a snapshot weakref
        memory.register_provider("kv_cache", self,
                                 lambda e: [e._ck, e._cv])

        self._worker = None
        if start:
            self._worker = threading.Thread(
                target=self._loop, daemon=True,
                name="mxnet_tpu.serving.generation.engine")
            self._worker.start()

    # -- properties ----------------------------------------------------------

    @property
    def max_slots(self):
        return self._slots

    @property
    def max_len(self):
        return self._max_len

    @property
    def prefill_buckets(self):
        return self._buckets

    @property
    def cache(self):
        """The engine's ``"generation"`` :class:`CompileCache` — ``.misses``
        is the exact number of programs compiled so far."""
        return self._cache

    @property
    def live_slots(self):
        return self._live

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def load(self):
        """Occupancy the router balances on: (live + queued) / slots."""
        return (self._live + len(self._queue)) / float(self._slots)

    @property
    def closed(self):
        return self._closed

    # -- health --------------------------------------------------------------

    def healthy(self):
        """Liveness: (ok, detail). False only when the scheduler worker
        thread died while the engine still owes work (a closed engine's
        joined worker is fine, and manually-ticked engines have none)."""
        if (self._worker is not None and not self._worker.is_alive()
                and not self._closed):
            return False, "scheduler worker thread died"
        return True, "ok"

    def ready(self):
        """Readiness: (ok, reason) — the router's placement gate and the
        ``/readyz`` probe. Not ready while draining (closed), while the
        tick beacon is marked stalled by the watchdog, before any
        executable exists (warm() not run AND no traffic compiled one),
        or with the intake queue above the watermark."""
        if self._closed:
            return False, "closed (draining)"
        if self._beacon.stalled:
            return False, "scheduler stalled (watchdog)"
        if not self._warmed and not len(self._cache):
            return False, "warmup not run"
        return queue_ready(self._queue)

    def kv_slab_bytes(self):
        """Total device bytes the KV slab pins (both key and value
        arrays) — the number ``docs/faq/perf.md`` "Sizing the KV slab"
        budgets."""
        return int(self._ck.nbytes) + int(self._cv.nbytes)

    def bucket_for(self, n):
        for b in self._buckets:
            if b >= n:
                return b
        return None

    # -- client API ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens=64, eos_id=None, timeout=None):
        """Admit one prompt; returns a :class:`GenerationStream`
        immediately. ``timeout`` (seconds) is the SESSION deadline —
        checked every scheduler tick, in queue and mid-generation; expiry
        evicts the slot and fails the stream with
        :class:`DeadlineExceededError`. Raises ``QueueFullError`` /
        ``ServerClosedError`` synchronously (backpressure is a signal,
        not a stall)."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size < 1:
            raise MXNetError("empty prompt")
        if prompt.size > self._buckets[-1]:
            raise MXNetError(
                f"prompt of {prompt.size} tokens exceeds the largest "
                f"prefill bucket {self._buckets[-1]}")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if prompt.size + int(max_new_tokens) > self._max_len:
            raise MXNetError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slab capacity "
                f"{self._max_len} (MXNET_GENERATION_MAX_LEN)")
        deadline = (time.monotonic() + float(timeout)
                    if timeout is not None else None)
        stream = GenerationStream(self, prompt.size, max_new_tokens,
                                  deadline)
        sess = _Session(prompt, max_new_tokens,
                        self._eos_id if eos_id is None else eos_id,
                        deadline, stream)
        if tracing._enabled:
            sess.span = tracing.begin("generation.session", cat="generation",
                                      prompt_tokens=int(prompt.size),
                                      max_new_tokens=int(max_new_tokens))
        req = Request([prompt], 1, stream._future, deadline=deadline,
                      payload=sess)
        try:
            self._queue.put(req)
        except Exception as e:
            if sess.span is not None:
                sess.span.set(error=repr(e)).finish()
            raise
        if telemetry._enabled:
            telemetry.counter("serving.generation.sessions").inc()
        if health._enabled:
            # work is pending: the tick beacon's silence now counts as a
            # stall until the slab drains again
            self._beacon.arm()
        with self._work:
            # under the condition lock: concurrent submitters would lose
            # increments of a bare +=
            self.sessions_submitted += 1
            self._work.notify_all()
        return stream

    def generate(self, prompt, **kwargs):
        """Blocking convenience: submit and collect the full token list
        (the iterator's caller-runs assist drives ticks inline when the
        worker is idle)."""
        return list(self.submit(prompt, **kwargs))

    def warm(self, buckets=None):
        """Compile-ahead every generation executable: one prefill program
        per bucket plus THE decode program, counted exactly
        (``cache.misses`` delta). Prefill warms write garbage into a FREE
        slot (skipped, with a log, for buckets that cannot get one on an
        already-full slab — they were compiled by real traffic anyway) and
        the decode warm runs only while no session is live, so warming a
        serving engine never perturbs a session. Returns
        ``{"buckets", "compiles", "seconds", "cache_entries"}``."""
        import jax.numpy as jnp

        buckets = (self._buckets if buckets is None
                   else tuple(sorted({int(b) for b in buckets})))
        t0 = time.perf_counter()
        misses0 = self._cache.misses
        with self._tick_lock:
            free = next((i for i, s in enumerate(self._sessions)
                         if s is None), None)
            for b in buckets:
                if b not in self._buckets:
                    raise MXNetError(f"bucket {b} not in ladder "
                                     f"{self._buckets}")
                if free is None:
                    self._logger.warning(
                        "generation warmup: slab full, skipping prefill "
                        "warm for bucket %d", b)
                    continue
                fn = self._prefill_fn(b)
                _, self._ck, self._cv = fn(
                    self._params, self._ck, self._cv,
                    jnp.zeros((b,), jnp.int32), jnp.asarray(1, jnp.int32),
                    jnp.asarray(free, jnp.int32))
            if self._live == 0:
                fn = self._decode_fn()
                _, self._ck, self._cv = fn(
                    self._params, self._ck, self._cv,
                    jnp.asarray(self._last_tok), jnp.asarray(self._lengths))
        compiles = self._cache.misses - misses0
        seconds = time.perf_counter() - t0
        self._warmed = True           # readiness: warmup complete
        if telemetry._enabled:
            telemetry.counter("serving.generation.warmup_compiles").inc(
                compiles)
        self._logger.info(
            "generation warmup: %d bucket(s) + decode -> %d compile(s) in "
            "%.2fs (cache %r holds %d executables)", len(buckets), compiles,
            seconds, self._cache.name, len(self._cache))
        return {"buckets": list(buckets), "compiles": compiles,
                "seconds": seconds, "cache_entries": len(self._cache)}

    def close(self, timeout=None):
        """Graceful drain: stop admission (``ServerClosedError`` for new
        submits), keep ticking until every admitted AND queued session
        completes, join the worker. Idempotent. Deregisters the health
        probes — a deliberately closed engine must not pin ``/readyz``."""
        self._queue.close()
        self._closed = True
        with self._work:
            self._work.notify_all()
        if self._worker is not None and self._worker.is_alive():
            self._worker.join(timeout)
        health.unregister(self.health_name)
        self._beacon.idle()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self):
        return {"cache": self._cache.snapshot(),
                "buckets": list(self._buckets),
                "slots": self._slots, "live": self._live,
                "queued": len(self._queue),
                "sessions": self.sessions_submitted,
                "max_len": self._max_len,
                "kv_slab_bytes": self.kv_slab_bytes()}

    # -- compiled programs ---------------------------------------------------

    def _prefill_fn(self, bucket):
        """The bucket's prefill executable: prompt forward + slab write +
        greedy next token, slab buffers donated."""
        model, cache = self._model, self._cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, ck, cv, toks, length, slot):
                logits, ck, cv = model.prefill(params, ck, cv, toks,
                                               length, slot)
                return jnp.argmax(logits).astype(jnp.int32), ck, cv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("prefill", bucket, self._slots, self._max_len)
        return cache.get_or_build(key, build, persistent=False)

    def _decode_fn(self):
        """THE decode executable — one fused step over the whole slab,
        greedy sampling inside, slab buffers donated. Its key never
        changes, so continuous admission/eviction is hit-only."""
        model, cache = self._model, self._cache

        def build():
            import jax
            import jax.numpy as jnp

            def fn(params, ck, cv, tokens, positions):
                logits, ck, cv = model.decode_step(params, ck, cv, tokens,
                                                   positions)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), ck, cv

            return jax.jit(fn, donate_argnums=(1, 2))

        key = ("decode", self._slots, self._max_len)
        return cache.get_or_build(key, build, persistent=False)

    # -- scheduler -----------------------------------------------------------

    def _has_work(self):
        return self._live > 0 or len(self._queue) > 0

    def _loop(self):
        while True:
            with self._work:
                while not self._closed and not self._has_work():
                    self._work.wait()
                if self._closed and not self._has_work():
                    return
            self._tick_once()

    def _assist_once(self):
        """Caller-runs assist (stream iterators call this while waiting):
        run one tick inline if the tick lock is free. Returns True when a
        tick ran (or there was nothing to do), False when the worker (or
        another assistant) holds the lock — the caller should briefly
        park instead of spinning."""
        if not self._tick_lock.acquire(blocking=False):
            return False
        try:
            if self._has_work():
                self._tick()
            return True
        finally:
            self._tick_lock.release()

    def _tick_once(self):
        with self._tick_lock:
            if self._has_work():
                self._tick()

    def _tick(self):
        """One scheduler tick (tick lock held): sweep deadlines, admit
        prefills into free slots, run ONE fused decode over the slab,
        evict finished sessions. A tick never raises — an executable
        failure fails the live sessions (never-strand, the batcher's
        guard) and reallocates the possibly-donated slab."""
        tele = telemetry._enabled
        t0 = time.perf_counter()
        # the tick's own span tree (admit/decode children via the context
        # var; per-SESSION spans keep their explicit session parents) —
        # observed into tracing.tick_recorder, the generation analog of
        # the slow-step flight recorder (/trace serves it as worst_tick)
        tick_span = tracing.span("generation.tick", cat="generation",
                                 live=self._live, queued=len(self._queue))
        with tick_span:
            try:
                now = time.monotonic()
                for req in self._queue.expire(now):
                    self._fail_queued(req.payload, now)
                for slot, sess in enumerate(self._sessions):
                    if (sess is not None and sess.deadline is not None
                            and now >= sess.deadline):
                        self._evict(slot, "deadline", DeadlineExceededError(
                            f"session deadline passed after "
                            f"{sess.generated} generated token(s)"))
                self._admit()
                self._decode()
            except Exception as e:  # noqa: BLE001 — never-strand + serve on
                self._logger.error("generation tick failed: %r", e)
                tick_span.set(error=repr(e))
                for slot, sess in enumerate(self._sessions):
                    if sess is not None:
                        self._evict(slot, "error", e)
                # the failed executable may have consumed the donated slab
                self._ck, self._cv = self._model.init_cache(self._slots,
                                                            self._max_len)
        if tracing._enabled:
            tracing.tick_recorder.observe(tick_span.tree())
        if health._enabled:
            # progress beacon: the tick RAN (even a failed one evicted and
            # reallocated — that is progress, not a stall); an empty slab
            # parks the scheduler, so silence while idle is not a stall
            self._beacon.touch()
            if not self._has_work():
                self._beacon.idle()
        if tele:
            dt = time.perf_counter() - t0
            telemetry.counter("serving.generation.ticks").inc()
            telemetry.histogram("serving.generation.tick_us").record(dt * 1e6)
            telemetry.gauge("serving.generation.live_slots").set(self._live)
            now = time.monotonic()
            if not self._has_work():
                # going idle: an un-reset gauge would report the last
                # active window's rate forever (the parked scheduler
                # never recomputes it)
                telemetry.gauge("serving.generation.tokens_per_s").set(0.0)
                self._tokens_window = 0
                self._rate_t0 = now
            elif now - self._rate_t0 >= 0.5:
                telemetry.gauge("serving.generation.tokens_per_s").set(
                    self._tokens_window / (now - self._rate_t0))
                self._tokens_window = 0
                self._rate_t0 = now

    def _admit(self):
        """Move queued sessions into free slots (prefill), oldest first,
        until the slab is full, the queue is empty, or the tick budget is
        spent — at least one admission per tick when a slot is free, so
        backlog always drains even under a tiny budget."""
        free = [i for i, s in enumerate(self._sessions) if s is None]
        if not free:
            return
        t0 = time.perf_counter()
        tele = telemetry._enabled
        with tracing.span("generation.admit", cat="generation",
                          free=len(free)):
            self._admit_into(free, t0, tele)

    def _admit_into(self, free, t0, tele):
        import jax.numpy as jnp

        while free:
            batch, _ = self._queue.get_batch_nowait(1)
            if not batch:
                return
            sess = batch[0].payload
            now = time.monotonic()
            if sess.deadline is not None and now >= sess.deadline:
                self._fail_queued(sess, now)
                continue
            slot = free.pop(0)
            n = int(sess.prompt.size)
            bucket = self.bucket_for(n)
            padded = np.zeros(bucket, np.int32)
            padded[:n] = sess.prompt
            t_pf = time.perf_counter()
            trc = tracing._enabled and sess.span is not None
            if trc:
                # queue-wait child reconstructed from the submit instant
                tracing.emit_span("generation.queued", sess.span.t0,
                                  tracing.now_us() - sess.span.t0,
                                  cat="generation", parent=sess.span)
                t_pf_us = tracing.now_us()
            fn = self._prefill_fn(bucket)
            try:
                tok, self._ck, self._cv = fn(
                    self._params, self._ck, self._cv, jnp.asarray(padded),
                    jnp.asarray(n, jnp.int32), jnp.asarray(slot, jnp.int32))
            except Exception as e:
                # the popped session is in neither the queue nor a slot —
                # the tick handler only evicts ADMITTED sessions, so fail
                # its stream here or it is stranded forever (never-strand,
                # the batcher's guard); re-raise for the slab reallocation
                if tele:
                    telemetry.counter("serving.generation.evictions").inc()
                    telemetry.counter("serving.generation.evict_error").inc()
                sess.stream._fail(e)
                if sess.span is not None:
                    sess.span.set(error=repr(e), reason="error").finish()
                raise
            tok = int(tok)
            if trc:
                tracing.emit_span("generation.prefill", t_pf_us,
                                  tracing.now_us() - t_pf_us,
                                  cat="generation", parent=sess.span,
                                  bucket=bucket, slot=slot)
            sess.slot = slot
            self._sessions[slot] = sess
            self._lengths[slot] = n
            self._last_tok[slot] = tok
            self._live += 1
            self._deliver(sess, tok, first=True)
            if tele:
                telemetry.counter("serving.generation.prefills").inc()
                telemetry.histogram("serving.generation.prefill_us").record(
                    (time.perf_counter() - t_pf) * 1e6)
            # the prompt's last token may already end the session; a slot
            # freed that way goes straight back on the free list so a
            # burst of first-token-EOS sessions drains within the tick
            self._maybe_finish(slot)
            if self._sessions[slot] is None:
                free.append(slot)
            if time.perf_counter() - t0 > self._tick_budget_s:
                return

    def _decode(self):
        """ONE fused decode step over the whole slab; every live session
        advances one token. Dead slots ride along as masked garbage —
        that fixed shape is exactly what makes mid-stream admit/evict
        free."""
        import jax.numpy as jnp

        if self._live == 0:
            return
        fn = self._decode_fn()
        with tracing.span("generation.decode", cat="generation",
                          live=self._live):
            toks, self._ck, self._cv = fn(
                self._params, self._ck, self._cv,
                jnp.asarray(self._last_tok), jnp.asarray(self._lengths))
            toks = np.asarray(toks)
        trc = tracing._enabled
        if trc:
            t_us = tracing.now_us()
        live = 0
        for slot, sess in enumerate(self._sessions):
            if sess is None:
                continue
            live += 1
            # the token we fed now occupies position lengths[slot]
            self._lengths[slot] += 1
            tok = int(toks[slot])
            self._last_tok[slot] = tok
            if trc and sess.span is not None:
                tracing.emit_span("generation.decode_tick", t_us, 0.0,
                                  cat="generation", parent=sess.span,
                                  position=int(self._lengths[slot]))
            self._deliver(sess, tok)
            self._maybe_finish(slot)
        if telemetry._enabled:
            telemetry.counter("serving.generation.decode_tokens").inc(live)
            telemetry.counter("serving.generation.tick_slots").inc(
                self._slots)

    # -- delivery / eviction -------------------------------------------------

    def _deliver(self, sess, tok, first=False):
        sess.generated += 1
        sess.stream._push(tok)
        self._tokens_window += 1
        if telemetry._enabled:
            telemetry.counter("serving.generation.tokens").inc()
            if first:
                telemetry.histogram("serving.generation.ttft_us").record(
                    (time.monotonic() - sess.stream.submitted_at) * 1e6)

    def _maybe_finish(self, slot):
        """Evict the slot if its session just reached a terminal state."""
        sess = self._sessions[slot]
        if sess.eos_id is not None and self._last_tok[slot] == sess.eos_id:
            self._evict(slot, "eos")
        elif sess.generated >= sess.max_new_tokens:
            self._evict(slot, "finished")
        elif self._lengths[slot] + 1 > self._max_len:
            # no room to write the next token's K/V — the slab, not the
            # request, is the binding constraint here
            self._evict(slot, "max_len")

    def _evict(self, slot, reason, exc=None):
        """Free the slot: host metadata only — the KV rows stay as masked
        garbage until the next occupant's prefill rewrites them."""
        sess = self._sessions[slot]
        self._sessions[slot] = None
        self._lengths[slot] = 0
        self._last_tok[slot] = 0
        self._live -= 1
        if telemetry._enabled:
            telemetry.counter("serving.generation.evictions").inc()
            telemetry.counter(f"serving.generation.evict_{reason}").inc()
        if health._enabled and reason not in ("eos", "finished"):
            # journal only the ABNORMAL evictions (deadline/max_len/error)
            # — normal completions would drown the ring
            health.event("generation_evict", engine=self.health_name,
                         slot=slot, reason=reason,
                         tokens=sess.generated)
        if exc is not None:
            sess.stream._fail(exc)
        else:
            sess.stream._finish()
        if sess.span is not None:
            t_us = tracing.now_us()
            tracing.emit_span("generation.evict", t_us, 0.0,
                              cat="generation", parent=sess.span,
                              reason=reason)
            sess.span.set(reason=reason, tokens=sess.generated,
                          **({"error": repr(exc)} if exc is not None else {}))
            sess.span.finish()

    def _fail_queued(self, sess, now):
        """Deadline death while still queued: no slot to free, just the
        stream to unblock (and the span tree to close)."""
        exc = DeadlineExceededError(
            f"session waited {now - sess.stream.submitted_at:.3f}s in "
            "queue, past its deadline")
        if telemetry._enabled:
            telemetry.counter("serving.generation.evict_deadline").inc()
            telemetry.counter("serving.generation.evictions").inc()
        if health._enabled:
            health.event("generation_evict", engine=self.health_name,
                         reason="deadline", queued=True)
        sess.stream._fail(exc)
        if sess.span is not None:
            sess.span.set(error=repr(exc), reason="deadline").finish()
