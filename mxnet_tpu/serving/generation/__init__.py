"""mxnet_tpu.serving.generation — continuous-batching autoregressive serving.

PR 5's serving layer batches STATELESS one-shot requests; this subsystem
serves token-by-token generation, the millions-of-users workload:

* :class:`GenerationEngine` — a slot-based KV-cache session store (one
  preallocated slab, fixed shapes, admission/eviction = a slot-index
  write) driven by a token-level continuous scheduler: each tick runs ONE
  fused ``decode_step`` over every live session, evicts finished/EOS/
  deadline-expired sequences and admits queued prefills into the freed
  slots mid-stream — O(1) per token, zero steady-state compiles
  (arXiv:2603.09555's compile-once cache discipline through
  ``CompileCache("generation")``);
* :class:`GenerationStream` — ``submit() → iterator of tokens`` with
  caller-runs assist, plus ``result()`` for collectors; failures
  (deadline, engine error) raise in-band instead of wedging the iterator;
* :class:`GenerationRouter` — spreads sessions across N engine replicas
  by live-slot occupancy with queue-full failover.

Quick start::

    lm = TransformerLM(cfg, mesh)
    eng = generation.GenerationEngine(lm, params, max_slots=16)
    serving.warmup(eng)                      # pin prefill+decode compiles
    stream = eng.submit(prompt_ids, max_new_tokens=64, timeout=2.0)
    for tok in stream:                       # tokens as they decode
        ...
"""
from .engine import GenerationEngine, prefill_ladder
from .router import GenerationRouter
from .session import GenerationStream

__all__ = ["GenerationEngine", "GenerationRouter", "GenerationStream",
           "prefill_ladder"]
