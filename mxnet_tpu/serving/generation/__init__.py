"""mxnet_tpu.serving.generation — continuous-batching autoregressive serving.

PR 5's serving layer batches STATELESS one-shot requests; this subsystem
serves token-by-token generation, the millions-of-users workload:

* :class:`GenerationEngine` — a slot-based KV-cache session store (one
  preallocated slab, fixed shapes, admission/eviction = a slot-index
  write) driven by a token-level continuous scheduler: each tick runs ONE
  fused ``decode_step`` over every live session, evicts finished/EOS/
  deadline-expired sequences and admits queued prefills into the freed
  slots mid-stream — O(1) per token, zero steady-state compiles
  (arXiv:2603.09555's compile-once cache discipline through
  ``CompileCache("generation")``);
* :class:`GenerationStream` — ``submit() → iterator of tokens`` with
  caller-runs assist, plus ``result()`` for collectors; failures
  (deadline, engine error) raise in-band instead of wedging the iterator;
* :class:`GenerationRouter` — spreads sessions across N engine replicas
  by cached-prefix affinity then live-slot occupancy, with queue-full
  failover and an autoscale actuator (``scale_to`` / ``bind_autoscale``);
* :class:`~.prefix_cache.RadixPrefixCache` — refcounted radix trie over
  prompt tokens whose payloads are KV rows in the engine's slab: shared
  prefixes prefill once and FORK into sessions (one traced slot-to-slot
  copy + a suffix-only prefill), ``MXNET_GENERATION_PREFIX_CACHE=1``;
* :mod:`~.speculative` — draft models (``MXNET_GENERATION_DRAFT``
  checkpoint or n-gram fallback) for the ``MXNET_GENERATION_SPEC_K``
  verify lane: k proposed tokens per tick checked by ONE fixed-shape
  slab-wide executable, greedy output bit-exact with plain decode.

Quick start::

    lm = TransformerLM(cfg, mesh)
    eng = generation.GenerationEngine(lm, params, max_slots=16)
    serving.warmup(eng)                      # pin prefill+decode compiles
    stream = eng.submit(prompt_ids, max_new_tokens=64, timeout=2.0)
    for tok in stream:                       # tokens as they decode
        ...
"""
from . import speculative
from .engine import GenerationEngine, prefill_ladder
from .prefix_cache import RadixPrefixCache
from .router import GenerationRouter
from .session import GenerationStream
from .speculative import (CheckpointDraft, NgramDraft, load_draft,
                          save_draft)

__all__ = ["GenerationEngine", "GenerationRouter", "GenerationStream",
           "RadixPrefixCache", "NgramDraft", "CheckpointDraft",
           "save_draft", "load_draft", "prefill_ladder", "speculative"]
